"""Chaos-pinned slice-transaction atomicity (the PR's acceptance bar):
the leader master is SIGKILL'd after k of n hosts attached — after
failover, either all n hosts hold chips under ONE slice-group lease or
all k are rolled back. Zero half-attached slices, zero double-actuation,
verified against the cross-replica store view."""

import json
import threading
import time

import pytest

from gpumounter_tpu.master.admission import BrokerConfig
from gpumounter_tpu.master.store import SliceTxnRecord
from gpumounter_tpu.testing.chaos import assert_slice_invariants
from gpumounter_tpu.testing.sim import MultiMasterStack, WorkerRig
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import HostPaths

NS = consts.DEFAULT_POOL_NAMESPACE

SLICE_BODY = json.dumps({
    "pods": [{"namespace": "default", "pod": "workload-0"},
             {"namespace": "default", "pod": "workload-1"}],
    "tpusPerHost": 4}).encode()


class _MasterCrash(BaseException):
    """Simulated master death mid-fan-out. A BaseException on purpose:
    it must skip every Exception-typed cleanup handler on its way out —
    no rollback, no terminal txn record, exactly what SIGKILL leaves."""


def _host(tmp_path, i):
    base = tmp_path / f"node{i}"
    for sub in ("dev", "proc", "sys/fs/cgroup"):
        (base / sub).mkdir(parents=True)
    return HostPaths(dev_root=str(base / "dev"),
                     proc_root=str(base / "proc"),
                     sys_root=str(base / "sys"),
                     cgroup_root=str(base / "sys" / "fs" / "cgroup"),
                     kubelet_socket=str(base / "pr" / "kubelet.sock"))


def _stack(tmp_path, queue_timeout_s):
    rigs = [WorkerRig(_host(tmp_path, i), n_chips=4, node=f"node-{i}",
                      pod_name=f"workload-{i}") for i in range(2)]
    return MultiMasterStack(
        rigs=rigs, masters=2, shards=1,
        broker_config=BrokerConfig(queue_timeout_s=queue_timeout_s,
                                   tick_interval_s=0.1))


def _store_slice_records(kube) -> list[SliceTxnRecord]:
    from gpumounter_tpu.utils.errors import K8sApiError
    try:
        cm = kube.get_config_map(NS, f"{consts.STORE_CONFIGMAP_PREFIX}0")
    except K8sApiError:
        return []
    out = []
    for key, value in (cm["metadata"].get("annotations") or {}).items():
        if key.startswith(consts.STORE_SLICE_ANNOTATION_PREFIX):
            out.append(SliceTxnRecord.from_json(value))
    return out


def _crash_leader_mid_fanout(stack, leader):
    """Run the slice attach on the leader and kill it between hosts:
    workload-0's host lands (commit marker persisted), workload-1's
    never starts. Returns once the crash has happened."""
    gateway = stack.gateways[leader]
    # freeze the doomed leader's maintenance loop first: a live master
    # SELF-heals a crashed fan-out thread from its own tick (stranded-
    # record adoption), but a SIGKILL'd process ticks nothing — the test
    # must leave the record for the SURVIVOR
    gateway.broker.stop()
    crashed = threading.Event()

    def before_host_attach(namespace, pod):
        if pod != "workload-1":
            return
        # let host-0 land AND its commit marker reach the store first —
        # the crash must leave a record saying exactly who holds chips
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            records = _store_slice_records(stack.kube)
            if any("default/workload-0" in record.committed
                   for record in records):
                break
            time.sleep(0.01)
        crashed.set()
        raise _MasterCrash()

    gateway.slices.before_host_attach = before_host_attach

    def run():
        try:
            gateway.handle("POST", "/addtpuslice", SLICE_BODY)
        except BaseException:   # noqa: BLE001 — the simulated SIGKILL
            pass

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    assert crashed.wait(timeout=30), "crash point never armed"
    thread.join(timeout=10)
    # assert the torn mid-state while the frozen leader still HOLDS the
    # lock (no peer may adopt yet): exactly one unresolved txn record
    # with host-0's commit marker — the breadcrumb the survivor adopts —
    # and exactly host-0 holding chips. Then kill the leader.
    records = _store_slice_records(stack.kube)
    assert len(records) == 1
    assert records[0].committed == ["default/workload-0"]
    assert len(stack.rigs[0].sim.slave_pods()) == 1
    assert stack.rigs[1].sim.slave_pods() == []
    stack.kill(leader)
    return records[0]


def _wait(predicate, timeout_s=20.0, message=""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(message or "condition never held")


def test_leader_killed_mid_fanout_survivor_completes_the_slice(tmp_path):
    """Deadline still open at failover ⇒ the survivor finishes the
    fan-out under the ORIGINAL rid: host-0 re-runs as an idempotent
    resume (no double actuation), host-1 attaches fresh, and one
    slice-group lease spans both — all n hosts, exactly once."""
    stack = _stack(tmp_path, queue_timeout_s=30.0)
    try:
        stack.wait_converged()
        leader = stack.leader_for("default")
        record = _crash_leader_mid_fanout(stack, leader)
        survivor = stack.gateways[next(iter(stack.live()))]
        _wait(lambda: not _store_slice_records(stack.kube),
              message="survivor never resolved the stranded slice txn")
        _wait(lambda: len(survivor.broker.leases.group_leases(
            record.txn_id)) == 2,
            message="survivor did not record the slice-group lease")
        # all n hosts hold chips, exactly one slave pod each
        for rig in stack.rigs:
            assert len(rig.sim.slave_pods()) == 1
        leases = survivor.broker.leases.group_leases(record.txn_id)
        assert {lease.pod for lease in leases} == {"workload-0",
                                                   "workload-1"}
        assert all(lease.chips == 4 for lease in leases)
        # zero double-actuation: each pod has at most ONE TPUAttached
        # (the adopted re-run of host-0 records TPUAttachResumed)
        for rig in stack.rigs:
            attached = [e for e in rig.sim.kube.events
                        if e.get("reason") == "TPUAttached"]
            assert len(attached) <= 1, [e["message"] for e in attached]
        assert_slice_invariants(survivor.broker,
                                [rig.sim for rig in stack.rigs],
                                store=survivor.broker.store)
    finally:
        stack.close()


def test_leader_killed_mid_fanout_expired_txn_rolls_back(tmp_path):
    """Deadline already passed at failover ⇒ the survivor rolls every
    member back via the txn-targeted detach: zero half-attached slices,
    host-0's chips drain back to the pool."""
    stack = _stack(tmp_path, queue_timeout_s=0.0)
    try:
        stack.wait_converged()
        leader = stack.leader_for("default")
        _crash_leader_mid_fanout(stack, leader)
        survivor = stack.gateways[next(iter(stack.live()))]
        _wait(lambda: not _store_slice_records(stack.kube),
              message="survivor never resolved the stranded slice txn")
        _wait(lambda: all(not rig.sim.slave_pods()
                          for rig in stack.rigs),
              message="rollback left a half-attached slice behind")
        assert survivor.broker.leases.groups() == {}
        assert_slice_invariants(survivor.broker,
                                [rig.sim for rig in stack.rigs],
                                store=survivor.broker.store)
    finally:
        stack.close()
