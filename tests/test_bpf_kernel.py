"""Kernel-proven cgroup-v2 device-gate tests (root + CAP_BPF gated).

Round-1 pinned the codegen semantics with an interpreter but never executed
``bpfgate_sync``'s query/load/replace sequence against a kernel (VERDICT
weak #7); and the sync assumed runc defaults as the baseline, silently
revoking runtime-granted devices (VERDICT missing #3). These tests mount a
private cgroup2 hierarchy, attach a "runtime" program the way runc would
(ALLOW_MULTI) carrying a NON-default device rule, run the production sync
composed from the container's observed /dev, then read back the attached
program's xlated instructions and execute the same interpreter the codegen
tests use — proving on this kernel that:

- the replace path works (attr layouts, fd plumbing, flags);
- the pre-existing non-default grant survives the sync;
- the chip rules are now allowed and everything else still denied.

Skips (not fails) without root or where cgroup2/bpf are unavailable, so the
suite stays green on unprivileged CI; the bench host runs them for real.
"""

import ctypes
import os
import subprocess
import sys

import pytest

from gpumounter_tpu.actuation.bpf import (ACC_MKNOD, ACC_READ, ACC_RW,
                                          ACC_RWM, BpfGate,
                                          CONTAINER_DEFAULT_RULES,
                                          DeviceRule, container_device_rules,
                                          rules_for_chips)
from gpumounter_tpu.device.fake import make_chips
from tests.test_bpf_gate import DEV_BLOCK, DEV_CHAR, interpret

pytestmark = pytest.mark.skipif(
    os.geteuid() != 0, reason="kernel BPF tests need root")


@pytest.fixture
def cg2(tmp_path):
    """A private cgroup2 mount with one scratch child cgroup."""
    mnt = tmp_path / "cg2"
    mnt.mkdir()
    try:
        subprocess.run(["mount", "-t", "cgroup2", "none", str(mnt)],
                       check=True, capture_output=True)
        if not (mnt / "cgroup.controllers").exists():
            raise OSError("mount reported success but no cgroup2 appeared")
        child = mnt / "tpumounter-test"
        child.mkdir()
    except (subprocess.CalledProcessError, OSError) as e:
        subprocess.run(["umount", "-l", str(mnt)], capture_output=True)
        pytest.skip(f"cannot mount a private cgroup2 here: {e}")
    yield str(child)
    subprocess.run(["umount", "-l", str(mnt)], capture_output=True)


@pytest.fixture
def gate():
    g = BpfGate()
    if not g.supported():
        pytest.skip("kernel refuses CGROUP_DEVICE prog load (no CAP_BPF?)")
    return g


# the non-default device a runtime might have granted (e.g. /dev/net/tun)
RUNTIME_EXTRA = DeviceRule("c", ACC_RW, 10, 200)
CHIP_MAJOR = 120


def _attach_runtime_program(gate, cgroup):
    gate.attach(cgroup, list(CONTAINER_DEFAULT_RULES) + [RUNTIME_EXTRA])
    assert gate.attached_count(cgroup) == 1


def test_sync_replaces_and_preserves_nondefault_rule(gate, cg2):
    """The VERDICT missing-#3 scenario end-to-end on a real kernel."""
    _attach_runtime_program(gate, cg2)

    chips = make_chips(2, major=CHIP_MAJOR)
    # what a /dev scan of the container would observe for the extra node
    observed = [DeviceRule("c", ACC_RWM, 10, 200)]
    rc = gate.sync(cg2, rules_for_chips(chips, observed=observed))
    assert rc == BpfGate.SYNC_OK
    assert gate.attached_count(cg2) == 1        # replaced, not stacked

    prog = gate.read_attached(cg2)
    # chip nodes now allowed
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 1
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 1) == 1
    # the pre-existing non-default grant SURVIVED the replacement
    assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1
    # defaults intact, arbitrary devices still denied
    assert interpret(prog, DEV_CHAR, ACC_RWM, 1, 3) == 1      # /dev/null
    assert interpret(prog, DEV_CHAR, ACC_READ, 9, 9) == 0
    assert interpret(prog, DEV_BLOCK, ACC_READ, 8, 0) == 0


def test_sync_noop_when_no_program_attached(gate, cg2):
    rc = gate.sync(cg2, rules_for_chips(make_chips(1)))
    assert rc == BpfGate.SYNC_NOOP
    assert gate.attached_count(cg2) == 0


def test_sync_revoke_removes_chip_keeps_rest(gate, cg2):
    """Detach direction: re-sync without the chip; the chip rule is gone,
    defaults + runtime extras stay."""
    _attach_runtime_program(gate, cg2)
    observed = [DeviceRule("c", ACC_RWM, 10, 200)]
    chips = make_chips(1, major=CHIP_MAJOR)
    assert gate.sync(cg2, rules_for_chips(chips, observed=observed)) == 1
    assert gate.sync(cg2, rules_for_chips([], observed=observed)) == 1

    prog = gate.read_attached(cg2)
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 0   # revoked
    assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1         # kept
    assert interpret(prog, DEV_CHAR, ACC_RWM, 1, 3) == 1           # kept


def test_observed_dev_scan_feeds_sync_end_to_end(gate, cg2, tmp_path):
    """Full composition path: a real char node in the container's /dev
    (via the procfs-root view) is discovered by container_device_rules and
    survives the production sync."""
    proc_root = tmp_path / "proc"
    dev = proc_root / "4242" / "root" / "dev" / "net"
    dev.mkdir(parents=True)
    try:
        os.mknod(str(dev / "tun"), 0o666 | 0o020000,  # S_IFCHR
                 os.makedev(10, 200))
    except OSError as e:
        pytest.skip(f"mknod denied: {e}")

    observed = container_device_rules(str(proc_root), 4242)
    assert DeviceRule("c", ACC_RWM, 10, 200) in observed

    _attach_runtime_program(gate, cg2)
    assert gate.sync(cg2, rules_for_chips(make_chips(1, major=CHIP_MAJOR),
                                          observed=observed)) == 1
    prog = gate.read_attached(cg2)
    assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 1
