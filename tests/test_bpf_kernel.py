"""Kernel-proven cgroup-v2 device-gate tests (root + CAP_BPF gated).

Round-1 pinned the codegen semantics with an interpreter but never executed
``bpfgate_sync``'s query/load/replace sequence against a kernel (VERDICT
weak #7); and the sync assumed runc defaults as the baseline, silently
revoking runtime-granted devices (VERDICT missing #3). These tests mount a
private cgroup2 hierarchy, attach a "runtime" program the way runc would
(ALLOW_MULTI) carrying a NON-default device rule, run the production sync
composed from the container's observed /dev, then read back the attached
program's xlated instructions and execute the same interpreter the codegen
tests use — proving on this kernel that:

- the replace path works (attr layouts, fd plumbing, flags);
- the pre-existing non-default grant survives the sync;
- the chip rules are now allowed and everything else still denied.

Skips (not fails) without root or where cgroup2/bpf are unavailable, so the
suite stays green on unprivileged CI; the bench host runs them for real.
"""

import ctypes
import os
import subprocess
import sys

import pytest

from gpumounter_tpu.actuation.bpf import (ACC_MKNOD, ACC_READ, ACC_RW,
                                          ACC_RWM, BpfGate,
                                          CONTAINER_DEFAULT_RULES,
                                          DeviceRule, container_device_rules,
                                          rules_for_chips)
from gpumounter_tpu.device.fake import make_chips
from tests.test_bpf_gate import DEV_BLOCK, DEV_CHAR, interpret

pytestmark = pytest.mark.skipif(
    os.geteuid() != 0, reason="kernel BPF tests need root")


@pytest.fixture
def cg2(tmp_path):
    """A private cgroup2 mount with one scratch child cgroup.

    The mount exposes the single kernel-wide cgroup2 hierarchy, so a child
    cgroup left behind by a previous run (e.g. after a lazy umount) would
    make a fixed-name mkdir fail with EEXIST forever — round-2 VERDICT weak
    #1: the "kernel-proven" tests silently degraded to skipped on every
    re-run. Hence: a unique child name per invocation, rmdir of any stale
    ``tpumounter-test*`` siblings, and rmdir-before-umount on teardown."""
    mnt = tmp_path / "cg2"
    mnt.mkdir()
    try:
        subprocess.run(["mount", "-t", "cgroup2", "none", str(mnt)],
                       check=True, capture_output=True)
        if not (mnt / "cgroup.controllers").exists():
            raise OSError("mount reported success but no cgroup2 appeared")
        for stale in mnt.glob("tpumounter-test*"):
            try:
                stale.rmdir()       # empty cgroup dirs only; busy ones stay
            except OSError:
                pass
        child = mnt / f"tpumounter-test-{os.getpid()}-{os.urandom(4).hex()}"
        child.mkdir()
    except (subprocess.CalledProcessError, OSError) as e:
        subprocess.run(["umount", "-l", str(mnt)], capture_output=True)
        pytest.skip(f"cannot mount a private cgroup2 here: {e}")
    yield str(child)
    try:
        child.rmdir()               # before umount, so the hierarchy is clean
    except OSError:
        pass
    subprocess.run(["umount", str(mnt)], capture_output=True)
    subprocess.run(["umount", "-l", str(mnt)], capture_output=True)


@pytest.fixture
def gate():
    g = BpfGate()
    if not g.supported():
        pytest.skip("kernel refuses CGROUP_DEVICE prog load (no CAP_BPF?)")
    return g


# the non-default device a runtime might have granted (e.g. /dev/net/tun)
RUNTIME_EXTRA = DeviceRule("c", ACC_RW, 10, 200)
CHIP_MAJOR = 120


def _attach_runtime_program(gate, cgroup):
    gate.attach(cgroup, list(CONTAINER_DEFAULT_RULES) + [RUNTIME_EXTRA])
    assert gate.attached_count(cgroup) == 1


def test_sync_replaces_and_preserves_nondefault_rule(gate, cg2):
    """The VERDICT missing-#3 scenario end-to-end on a real kernel."""
    _attach_runtime_program(gate, cg2)

    chips = make_chips(2, major=CHIP_MAJOR)
    # what a /dev scan of the container would observe for the extra node
    observed = [DeviceRule("c", ACC_RWM, 10, 200)]
    rc = gate.sync(cg2, rules_for_chips(chips, observed=observed))
    assert rc == BpfGate.SYNC_OK
    assert gate.attached_count(cg2) == 1        # replaced, not stacked

    prog = gate.read_attached(cg2)
    # chip nodes now allowed
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 1
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 1) == 1
    # the pre-existing non-default grant SURVIVED the replacement
    assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1
    # defaults intact, arbitrary devices still denied
    assert interpret(prog, DEV_CHAR, ACC_RWM, 1, 3) == 1      # /dev/null
    assert interpret(prog, DEV_CHAR, ACC_READ, 9, 9) == 0
    assert interpret(prog, DEV_BLOCK, ACC_READ, 8, 0) == 0


def test_sync_noop_when_no_program_attached(gate, cg2):
    rc = gate.sync(cg2, rules_for_chips(make_chips(1)))
    assert rc == BpfGate.SYNC_NOOP
    assert gate.attached_count(cg2) == 0


def test_sync_revoke_removes_chip_keeps_rest(gate, cg2):
    """Detach direction: re-sync without the chip; the chip rule is gone,
    defaults + runtime extras stay."""
    _attach_runtime_program(gate, cg2)
    observed = [DeviceRule("c", ACC_RWM, 10, 200)]
    chips = make_chips(1, major=CHIP_MAJOR)
    assert gate.sync(cg2, rules_for_chips(chips, observed=observed)) == 1
    assert gate.sync(cg2, rules_for_chips([], observed=observed)) == 1

    prog = gate.read_attached(cg2)
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 0   # revoked
    assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1         # kept
    assert interpret(prog, DEV_CHAR, ACC_RWM, 1, 3) == 1           # kept


def test_observed_dev_scan_feeds_sync_end_to_end(gate, cg2, tmp_path):
    """Full composition path: a real char node in the container's /dev
    (via the procfs-root view) is discovered by container_device_rules and
    survives the production sync."""
    proc_root = tmp_path / "proc"
    dev = proc_root / "4242" / "root" / "dev" / "net"
    dev.mkdir(parents=True)
    try:
        os.mknod(str(dev / "tun"), 0o666 | 0o020000,  # S_IFCHR
                 os.makedev(10, 200))
    except OSError as e:
        pytest.skip(f"mknod denied: {e}")

    observed = container_device_rules(str(proc_root), 4242)
    assert DeviceRule("c", ACC_RWM, 10, 200) in observed

    _attach_runtime_program(gate, cg2)
    assert gate.sync(cg2, rules_for_chips(make_chips(1, major=CHIP_MAJOR),
                                          observed=observed)) == 1
    prog = gate.read_attached(cg2)
    assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 1


def test_production_revoke_with_chip_still_in_dev(gate, cg2, tmp_path):
    """ADVICE r2 high: at detach time the chip's node is still present in
    the container's /dev (nodes are removed only after the cgroup sync), so
    the production observed-/dev composition used to re-grant the chip being
    revoked. Drive CgroupDeviceController.revoke_device_access end-to-end —
    live /dev scan included — and prove on this kernel that the detached
    chip is denied while the remaining chip and runtime extras survive."""
    from gpumounter_tpu.actuation.cgroup import CgroupDeviceController
    from gpumounter_tpu.utils.config import HostPaths

    uid = "11111111-2222-3333-4444-555555555555"
    cid_hex = "ab" * 32
    pod = {
        "metadata": {"name": "t", "namespace": "default", "uid": uid},
        "spec": {"containers": [{"name": "main", "resources": {
            "limits": {"cpu": "1", "memory": "1Gi"},
            "requests": {"cpu": "1", "memory": "1Gi"}}}]},
        "status": {"qosClass": "Guaranteed", "containerStatuses": [
            {"name": "main", "containerID": f"containerd://{cid_hex}"}]},
    }
    # container cgroup nested inside the scratch cgroup (real cgroup2 dirs)
    nested = [f"{cg2}/kubepods", f"{cg2}/kubepods/pod{uid}",
              f"{cg2}/kubepods/pod{uid}/{cid_hex}"]
    for d in nested:
        os.mkdir(d)
    container_cg = nested[-1]

    # A sacrificial live process joined into the container cgroup, whose
    # (fixture) /proc root/dev still holds BOTH chip nodes plus a
    # runtime-granted extra — exactly the mid-detach state.
    sleeper = subprocess.Popen(["sleep", "120"])
    proc_root = tmp_path / "proc"
    dev = proc_root / str(sleeper.pid) / "root" / "dev"
    dev.mkdir(parents=True)
    try:
        try:
            for name, major, minor in [("accel0", CHIP_MAJOR, 0),
                                       ("accel1", CHIP_MAJOR, 1),
                                       ("tun", 10, 200)]:
                os.mknod(str(dev / name), 0o666 | 0o020000,
                         os.makedev(major, minor))
        except OSError as e:
            pytest.skip(f"mknod denied: {e}")
        # cgroup2 cgroup.procs write MOVES the process into the cgroup —
        # this is a real member, so get_pids reads it back from the kernel
        with open(os.path.join(container_cg, "cgroup.procs"), "w") as f:
            f.write(str(sleeper.pid))

        _attach_runtime_program(gate, container_cg)

        host = HostPaths(proc_root=str(proc_root), cgroup_root=cg2)
        ctrl = CgroupDeviceController(host, driver="cgroupfs", version=2,
                                      bpf_gate=gate)
        chips = make_chips(2, major=CHIP_MAJOR)
        ctrl.revoke_device_access(pod, f"containerd://{cid_hex}",
                                  [chips[0]], [chips[1]])

        prog = gate.read_attached(container_cg)
        assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 0  # gone
        assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 1) == 1  # kept
        assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1        # kept
        assert interpret(prog, DEV_CHAR, ACC_RWM, 1, 3) == 1          # null
        assert interpret(prog, DEV_CHAR, ACC_READ, 9, 9) == 0         # deny
    finally:
        sleeper.kill()
        sleeper.wait()
        for d in reversed(nested):
            try:
                os.rmdir(d)
            except OSError:
                pass


# -- map-driven gate (PR 12): kernel-proven ------------------------------------

def _in_cgroup_open(cgroup: str, path: str, flags=os.O_RDONLY) -> int:
    """fork a child, move it into the cgroup, try open(2); returns 0 on
    success or the child's errno (EPERM = the device program denied)."""
    pid = os.fork()
    if pid == 0:
        try:
            with open(os.path.join(cgroup, "cgroup.procs"), "w") as f:
                f.write(str(os.getpid()))
            os.close(os.open(path, flags))
            os._exit(0)
        except OSError as e:
            os._exit(e.errno or 99)
    return os.waitstatus_to_exitcode(os.waitpid(pid, 0)[1])


def test_map_gate_grant_revoke_and_exact_counters(gate, cg2):
    """The PR 12 enforcement point against a live kernel: attach the
    map program over a runc-style baseline, prove grant/deny through
    real open(2) calls in the cgroup, revoke IN PLACE (map update, no
    program replacement), and read back the exact open/deny counters
    the program maintained."""
    _attach_runtime_program(gate, cg2)
    # grant /dev/null rwm + a read-only wildcard on major 1
    rules = [DeviceRule("c", ACC_RWM, 1, 3),
             DeviceRule("c", ACC_READ, 1, None)]
    rc, map_fd = gate.map_attach(cg2, rules)
    assert rc == BpfGate.MAP_ATTACHED and map_fd >= 0
    assert gate.attached_count(cg2) == 1          # replaced, not stacked
    try:
        assert _in_cgroup_open(cg2, "/dev/null") == 0
        assert _in_cgroup_open(cg2, "/dev/null", os.O_RDWR) == 0
        assert _in_cgroup_open(cg2, "/dev/zero") == 0      # via wildcard
        assert _in_cgroup_open(cg2, "/dev/zero",
                               os.O_RDWR) == 1             # EPERM: r only
        assert _in_cgroup_open(cg2, "/dev/tty") == 1       # ungranted
        # in-place revocation: drop the exact /dev/null grant
        gate.map_sync(map_fd, [DeviceRule("c", ACC_READ, 1, None)])
        assert gate.attached_count(cg2) == 1      # SAME program, new map
        assert _in_cgroup_open(cg2, "/dev/null",
                               os.O_RDWR) == 1             # now denied
        assert _in_cgroup_open(cg2, "/dev/null") == 0      # wildcard read
        live, opens, denies = gate.map_read(map_fd)
        assert {(r.dev_type, r.major, r.minor) for r in live} == \
            {("c", 1, None)}
        assert denies == 3                        # the three EPERMs above
        # adoption: a "restarted worker" recovers the SAME live map —
        # counters and policy survive the process death
        rc2, map_fd2 = gate.map_attach(cg2, [DeviceRule("c", ACC_READ,
                                                        1, None)])
        assert rc2 == BpfGate.MAP_ADOPTED
        _l, _o, denies2 = gate.map_read(map_fd2)
        assert denies2 == denies
        gate.map_close(map_fd2)
    finally:
        gate.map_close(map_fd)


def test_map_gate_noop_on_unrestricted_cgroup(gate, cg2):
    rc, map_fd = gate.map_attach(cg2, [DeviceRule("c", ACC_RWM, 1, 3)])
    assert rc == BpfGate.MAP_NOOP and map_fd == -1
    assert gate.attached_count(cg2) == 0


def test_map_recover_discovers_previous_incarnations_maps(gate, cg2):
    """Restart-time orphan discovery: a NEW worker process (fresh
    NativeGateBackend, empty fd cache) walks the cgroup tree, adopts a
    crash-surviving map via the recover-only probe, and the converge
    orphan sweep can then strip a dead owner's chip grants IN the kernel
    — the enumeration in-process state cannot provide."""
    from gpumounter_tpu.actuation.gate import NativeGateBackend
    rules = [DeviceRule("c", ACC_RWM, 1, 3),
             DeviceRule("c", ACC_RW, CHIP_MAJOR, 0)]
    # recover-only probe semantics on a directly-gated cgroup
    _attach_runtime_program(gate, cg2)
    rc, map_fd = gate.map_attach(cg2, rules)
    assert rc == BpfGate.MAP_ATTACHED
    gate.map_close(map_fd)                    # the old process died
    rc, fd = gate.map_recover(cg2)
    assert rc == BpfGate.MAP_ADOPTED and fd >= 0
    live, _opens, _denies = gate.map_read(fd)
    assert {(r.dev_type, r.major, r.minor) for r in live} == \
        {("c", 1, 3), ("c", CHIP_MAJOR, 0)}
    gate.map_close(fd)
    # ungated dir: no adoption, no mutation
    mnt = os.path.dirname(cg2)
    assert gate.map_recover(mnt)[0] == BpfGate.MAP_NOOP
    # the discovery WALK: stage a kubepods-shaped subtree holding a gated
    # container from the "previous incarnation", then point a FRESH
    # backend (empty fd cache — the restarted worker) at the root
    kube_top = os.path.join(mnt, "kubepods")
    nested = os.path.join(kube_top, "pod-dead", "container-x")
    os.makedirs(nested)
    try:
        _attach_runtime_program(gate, nested)
        rc, map_fd = gate.map_attach(nested, rules)
        assert rc == BpfGate.MAP_ATTACHED
        gate.map_close(map_fd)
        backend = NativeGateBackend(gate, cgroup_root=mnt)
        assert backend.discover() == 1
        assert nested in backend.keys()
        live, _o, _d = backend.read(nested)
        assert ("c", CHIP_MAJOR, 0) in live
        backend.remove(nested)
    finally:
        for d in (nested, os.path.dirname(nested), kube_top):
            try:
                os.rmdir(d)
            except OSError:
                pass
