"""Kernel-proven cgroup-v2 device-gate tests (root + CAP_BPF gated).

Round-1 pinned the codegen semantics with an interpreter but never executed
``bpfgate_sync``'s query/load/replace sequence against a kernel (VERDICT
weak #7); and the sync assumed runc defaults as the baseline, silently
revoking runtime-granted devices (VERDICT missing #3). These tests mount a
private cgroup2 hierarchy, attach a "runtime" program the way runc would
(ALLOW_MULTI) carrying a NON-default device rule, run the production sync
composed from the container's observed /dev, then read back the attached
program's xlated instructions and execute the same interpreter the codegen
tests use — proving on this kernel that:

- the replace path works (attr layouts, fd plumbing, flags);
- the pre-existing non-default grant survives the sync;
- the chip rules are now allowed and everything else still denied.

Skips (not fails) without root or where cgroup2/bpf are unavailable, so the
suite stays green on unprivileged CI; the bench host runs them for real.
"""

import ctypes
import os
import subprocess
import sys

import pytest

from gpumounter_tpu.actuation.bpf import (ACC_MKNOD, ACC_READ, ACC_RW,
                                          ACC_RWM, BpfGate,
                                          CONTAINER_DEFAULT_RULES,
                                          DeviceRule, container_device_rules,
                                          rules_for_chips)
from gpumounter_tpu.device.fake import make_chips
from tests.test_bpf_gate import DEV_BLOCK, DEV_CHAR, interpret

pytestmark = pytest.mark.skipif(
    os.geteuid() != 0, reason="kernel BPF tests need root")


@pytest.fixture
def cg2(tmp_path):
    """A private cgroup2 mount with one scratch child cgroup.

    The mount exposes the single kernel-wide cgroup2 hierarchy, so a child
    cgroup left behind by a previous run (e.g. after a lazy umount) would
    make a fixed-name mkdir fail with EEXIST forever — round-2 VERDICT weak
    #1: the "kernel-proven" tests silently degraded to skipped on every
    re-run. Hence: a unique child name per invocation, rmdir of any stale
    ``tpumounter-test*`` siblings, and rmdir-before-umount on teardown."""
    mnt = tmp_path / "cg2"
    mnt.mkdir()
    try:
        subprocess.run(["mount", "-t", "cgroup2", "none", str(mnt)],
                       check=True, capture_output=True)
        if not (mnt / "cgroup.controllers").exists():
            raise OSError("mount reported success but no cgroup2 appeared")
        for stale in mnt.glob("tpumounter-test*"):
            try:
                stale.rmdir()       # empty cgroup dirs only; busy ones stay
            except OSError:
                pass
        child = mnt / f"tpumounter-test-{os.getpid()}-{os.urandom(4).hex()}"
        child.mkdir()
    except (subprocess.CalledProcessError, OSError) as e:
        subprocess.run(["umount", "-l", str(mnt)], capture_output=True)
        pytest.skip(f"cannot mount a private cgroup2 here: {e}")
    yield str(child)
    try:
        child.rmdir()               # before umount, so the hierarchy is clean
    except OSError:
        pass
    subprocess.run(["umount", str(mnt)], capture_output=True)
    subprocess.run(["umount", "-l", str(mnt)], capture_output=True)


@pytest.fixture
def gate():
    g = BpfGate()
    if not g.supported():
        pytest.skip("kernel refuses CGROUP_DEVICE prog load (no CAP_BPF?)")
    return g


# the non-default device a runtime might have granted (e.g. /dev/net/tun)
RUNTIME_EXTRA = DeviceRule("c", ACC_RW, 10, 200)
CHIP_MAJOR = 120


def _attach_runtime_program(gate, cgroup):
    gate.attach(cgroup, list(CONTAINER_DEFAULT_RULES) + [RUNTIME_EXTRA])
    assert gate.attached_count(cgroup) == 1


def test_sync_replaces_and_preserves_nondefault_rule(gate, cg2):
    """The VERDICT missing-#3 scenario end-to-end on a real kernel."""
    _attach_runtime_program(gate, cg2)

    chips = make_chips(2, major=CHIP_MAJOR)
    # what a /dev scan of the container would observe for the extra node
    observed = [DeviceRule("c", ACC_RWM, 10, 200)]
    rc = gate.sync(cg2, rules_for_chips(chips, observed=observed))
    assert rc == BpfGate.SYNC_OK
    assert gate.attached_count(cg2) == 1        # replaced, not stacked

    prog = gate.read_attached(cg2)
    # chip nodes now allowed
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 1
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 1) == 1
    # the pre-existing non-default grant SURVIVED the replacement
    assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1
    # defaults intact, arbitrary devices still denied
    assert interpret(prog, DEV_CHAR, ACC_RWM, 1, 3) == 1      # /dev/null
    assert interpret(prog, DEV_CHAR, ACC_READ, 9, 9) == 0
    assert interpret(prog, DEV_BLOCK, ACC_READ, 8, 0) == 0


def test_sync_noop_when_no_program_attached(gate, cg2):
    rc = gate.sync(cg2, rules_for_chips(make_chips(1)))
    assert rc == BpfGate.SYNC_NOOP
    assert gate.attached_count(cg2) == 0


def test_sync_revoke_removes_chip_keeps_rest(gate, cg2):
    """Detach direction: re-sync without the chip; the chip rule is gone,
    defaults + runtime extras stay."""
    _attach_runtime_program(gate, cg2)
    observed = [DeviceRule("c", ACC_RWM, 10, 200)]
    chips = make_chips(1, major=CHIP_MAJOR)
    assert gate.sync(cg2, rules_for_chips(chips, observed=observed)) == 1
    assert gate.sync(cg2, rules_for_chips([], observed=observed)) == 1

    prog = gate.read_attached(cg2)
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 0   # revoked
    assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1         # kept
    assert interpret(prog, DEV_CHAR, ACC_RWM, 1, 3) == 1           # kept


def test_observed_dev_scan_feeds_sync_end_to_end(gate, cg2, tmp_path):
    """Full composition path: a real char node in the container's /dev
    (via the procfs-root view) is discovered by container_device_rules and
    survives the production sync."""
    proc_root = tmp_path / "proc"
    dev = proc_root / "4242" / "root" / "dev" / "net"
    dev.mkdir(parents=True)
    try:
        os.mknod(str(dev / "tun"), 0o666 | 0o020000,  # S_IFCHR
                 os.makedev(10, 200))
    except OSError as e:
        pytest.skip(f"mknod denied: {e}")

    observed = container_device_rules(str(proc_root), 4242)
    assert DeviceRule("c", ACC_RWM, 10, 200) in observed

    _attach_runtime_program(gate, cg2)
    assert gate.sync(cg2, rules_for_chips(make_chips(1, major=CHIP_MAJOR),
                                          observed=observed)) == 1
    prog = gate.read_attached(cg2)
    assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1
    assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 1


def test_production_revoke_with_chip_still_in_dev(gate, cg2, tmp_path):
    """ADVICE r2 high: at detach time the chip's node is still present in
    the container's /dev (nodes are removed only after the cgroup sync), so
    the production observed-/dev composition used to re-grant the chip being
    revoked. Drive CgroupDeviceController.revoke_device_access end-to-end —
    live /dev scan included — and prove on this kernel that the detached
    chip is denied while the remaining chip and runtime extras survive."""
    from gpumounter_tpu.actuation.cgroup import CgroupDeviceController
    from gpumounter_tpu.utils.config import HostPaths

    uid = "11111111-2222-3333-4444-555555555555"
    cid_hex = "ab" * 32
    pod = {
        "metadata": {"name": "t", "namespace": "default", "uid": uid},
        "spec": {"containers": [{"name": "main", "resources": {
            "limits": {"cpu": "1", "memory": "1Gi"},
            "requests": {"cpu": "1", "memory": "1Gi"}}}]},
        "status": {"qosClass": "Guaranteed", "containerStatuses": [
            {"name": "main", "containerID": f"containerd://{cid_hex}"}]},
    }
    # container cgroup nested inside the scratch cgroup (real cgroup2 dirs)
    nested = [f"{cg2}/kubepods", f"{cg2}/kubepods/pod{uid}",
              f"{cg2}/kubepods/pod{uid}/{cid_hex}"]
    for d in nested:
        os.mkdir(d)
    container_cg = nested[-1]

    # A sacrificial live process joined into the container cgroup, whose
    # (fixture) /proc root/dev still holds BOTH chip nodes plus a
    # runtime-granted extra — exactly the mid-detach state.
    sleeper = subprocess.Popen(["sleep", "120"])
    proc_root = tmp_path / "proc"
    dev = proc_root / str(sleeper.pid) / "root" / "dev"
    dev.mkdir(parents=True)
    try:
        try:
            for name, major, minor in [("accel0", CHIP_MAJOR, 0),
                                       ("accel1", CHIP_MAJOR, 1),
                                       ("tun", 10, 200)]:
                os.mknod(str(dev / name), 0o666 | 0o020000,
                         os.makedev(major, minor))
        except OSError as e:
            pytest.skip(f"mknod denied: {e}")
        # cgroup2 cgroup.procs write MOVES the process into the cgroup —
        # this is a real member, so get_pids reads it back from the kernel
        with open(os.path.join(container_cg, "cgroup.procs"), "w") as f:
            f.write(str(sleeper.pid))

        _attach_runtime_program(gate, container_cg)

        host = HostPaths(proc_root=str(proc_root), cgroup_root=cg2)
        ctrl = CgroupDeviceController(host, driver="cgroupfs", version=2,
                                      bpf_gate=gate)
        chips = make_chips(2, major=CHIP_MAJOR)
        ctrl.revoke_device_access(pod, f"containerd://{cid_hex}",
                                  [chips[0]], [chips[1]])

        prog = gate.read_attached(container_cg)
        assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 0) == 0  # gone
        assert interpret(prog, DEV_CHAR, ACC_RW, CHIP_MAJOR, 1) == 1  # kept
        assert interpret(prog, DEV_CHAR, ACC_RW, 10, 200) == 1        # kept
        assert interpret(prog, DEV_CHAR, ACC_RWM, 1, 3) == 1          # null
        assert interpret(prog, DEV_CHAR, ACC_READ, 9, 9) == 0         # deny
    finally:
        sleeper.kill()
        sleeper.wait()
        for d in reversed(nested):
            try:
                os.rmdir(d)
            except OSError:
                pass
