"""Multi-container pods: actuation and busy detection must cover EVERY
container, not just the first.

The reference used pids[0] of the first container (util.go:50), so a device
holder living in a second container was invisible to the busy pre-check and
detach could yank a device in active use — SURVEY.md §8 lists this as a
quirk not to replicate."""

import pytest

from gpumounter_tpu.testing.sim import WorkerRig, make_target_pod
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.errors import DeviceBusyError

CID_MAIN = "containerd://" + "ab" * 32
CID_SIDE = "containerd://" + "cd" * 32


def make_two_container_pod(name="multi", uid="uid-multi"):
    pod = make_target_pod(name=name, container_id=CID_MAIN, uid=uid)
    pod["spec"]["containers"].append({"name": "side", "resources": {}})
    pod["status"]["containerStatuses"].append(
        {"name": "side", "containerID": CID_SIDE})
    return pod


@pytest.fixture
def rig(fake_host):
    r = WorkerRig(fake_host, n_chips=4)
    yield r
    r.close()


@pytest.fixture
def multi_pod(rig):
    pod = make_two_container_pod()
    rig.sim.kube.put_pod(pod)
    pids = rig.provision_container(pod)
    return pod, pids


def test_mount_actuates_every_container(rig, multi_pod):
    pod, pids = multi_pod
    outcome = rig.service.add_tpu("multi", "default", 2, True)
    assert outcome.result == consts.AddResult.SUCCESS
    created_pids = {entry[0] for entry in rig.actuator.created}
    assert created_pids == set(pids.values())        # nodes in BOTH containers
    # and both containers' cgroups got device access
    for cid in (CID_MAIN, CID_SIDE):
        allow = rig.cgroups.container_dir(pod, cid) + "/devices.allow"
        with open(allow) as f:
            assert "c 120:" in f.read()


def test_holder_in_second_container_blocks_detach(rig, multi_pod):
    pod, pids = multi_pod
    outcome = rig.service.add_tpu("multi", "default", 2, True)
    chip = outcome.chips[0]
    side_pid = pids[CID_SIDE]
    rig.sim.enumerator.busy_pids = {chip.device_path: [side_pid]}

    result = rig.service.remove_tpu("multi", "default", [], force=False)
    assert result.result == consts.RemoveResult.TPU_BUSY
    assert result.busy_pids == [side_pid]
    assert len(rig.sim.slave_pods()) == 1            # nothing detached


def test_pod_device_processes_sees_all_containers(rig, multi_pod):
    pod, pids = multi_pod
    outcome = rig.service.add_tpu("multi", "default", 1, True)
    chip = outcome.chips[0]
    rig.sim.enumerator.busy_pids = {
        chip.device_path: [pids[CID_MAIN], pids[CID_SIDE]]}
    holders = rig.mounter.pod_device_processes(pod, chip)
    assert sorted(holders) == sorted(pids.values())


def test_force_detach_kills_holder_in_second_container(rig, multi_pod):
    pod, pids = multi_pod
    outcome = rig.service.add_tpu("multi", "default", 2, True)
    chip = outcome.chips[0]
    side_pid = pids[CID_SIDE]
    rig.sim.enumerator.busy_pids = {chip.device_path: [side_pid]}

    result = rig.service.remove_tpu("multi", "default", [], force=True)
    assert result.result == consts.RemoveResult.SUCCESS
    assert (side_pid, 9) in rig.actuator.killed
    # device nodes removed from both containers
    removed_pids = {entry[0] for entry in rig.actuator.removed}
    assert removed_pids == set(pids.values())


def test_dead_sidecar_does_not_block_actuation(rig):
    """A terminated sidecar keeps its containerID in pod status but has no
    cgroup: actuation must skip it and serve the live container (a completed
    init-style sidecar must not break AddTPU)."""
    pod = make_two_container_pod(name="deadside", uid="uid-deadside")
    rig.sim.kube.put_pod(pod)
    # provision ONLY the main container's cgroup; the sidecar is dead
    import os
    from gpumounter_tpu.k8s import objects
    cid = CID_MAIN
    cgroup_dir = rig.cgroups.container_dir(pod, cid)
    os.makedirs(cgroup_dir, exist_ok=True)
    with open(os.path.join(cgroup_dir, "cgroup.procs"), "w") as f:
        f.write("7777\n")
    os.makedirs(os.path.join(rig.host.proc_root, "7777"), exist_ok=True)

    outcome = rig.service.add_tpu("deadside", "default", 2, True)
    assert outcome.result == consts.AddResult.SUCCESS
    assert {entry[0] for entry in rig.actuator.created} == {7777}

    result = rig.service.remove_tpu("deadside", "default", [], force=False)
    assert result.result == consts.RemoveResult.SUCCESS
