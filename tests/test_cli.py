"""tpumounterctl against a live master+worker stack (same rig as test_e2e):
human output, --json output, exit codes, and the same-request-id retry
contract on transient transport failures."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gpumounter_tpu import cli
from tests.helpers import LiveStack, WorkerRig


@pytest.fixture
def live_stack(fake_host):
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True))
    yield stack.rig, stack.base
    stack.close()


def run_cli(base, *argv):
    import contextlib
    import io
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli.main(["--master", base, *argv])
    return rc, out.getvalue()


def test_add_status_remove_roundtrip(live_stack):
    rig, base = live_stack
    rc, out = run_cli(base, "add", "workload", "-n", "default",
                      "--tpus", "4", "--entire")
    assert rc == 0
    assert "SUCCESS" in out and "/dev/accel0" in out

    rc, out = run_cli(base, "status", "workload")
    assert rc == 0
    assert "mount_type=entire" in out
    assert out.count("via") == 4

    rc, out = run_cli(base, "remove", "workload", "--uuids", "0,1,2,3")
    assert rc == 0 and "SUCCESS" in out
    assert rig.sim.slave_pods() == []


def test_json_output_and_exit_codes(live_stack):
    rig, base = live_stack
    rc, out = run_cli(base, "--json", "add", "nosuchpod")
    assert rc == cli.EXIT_CODES["PodNotFound"]
    assert json.loads(out)["result"] == "PodNotFound"

    rc, out = run_cli(base, "--json", "add", "workload", "--tpus", "99")
    assert rc == cli.EXIT_CODES["InsufficientTPU"]

    rc, out = run_cli(base, "remove", "workload")
    assert rc == cli.EXIT_CODES["TPUNotFound"]

    rc, out = run_cli(base, "health")
    assert rc == 0 and "ok" in out


def test_transport_error_exit_code():
    rc = cli.main(["--master", "http://127.0.0.1:1", "--timeout", "1",
                   "status", "x"])
    assert rc == cli.EXIT_TRANSPORT


def test_retry_reuses_request_id(live_stack, monkeypatch):
    """The CLI's whole value-add: a transient failure is retried with the
    SAME X-Request-Id, which the gateway+allocator turn into a resume —
    one slave-pod set, not two."""
    rig, base = live_stack
    seen_rids = []
    real_request = cli._request
    calls = {"n": 0}

    def flaky(master, method, path, body=None, headers=None, timeout=60.0):
        calls["n"] += 1
        if headers and "X-Request-Id" in headers:
            seen_rids.append(headers["X-Request-Id"])
        if calls["n"] == 1:
            raise cli.TransportError("connection reset mid-reply")
        return real_request(master, method, path, body, headers, timeout)

    monkeypatch.setattr(cli, "_request", flaky)
    monkeypatch.setattr(cli.time, "sleep", lambda s: None)
    rc, out = run_cli(base, "add", "workload", "--tpus", "2", "--entire")
    assert rc == 0 and "SUCCESS" in out
    assert len(seen_rids) == 2 and seen_rids[0] == seen_rids[1]
    # one slave-pod set despite two attempts
    assert len(rig.sim.slave_pods()) == 1


def test_slice_pod_spec_parsing():
    assert cli._parse_slice_pods(["ns1/a", "b"]) == [
        {"namespace": "ns1", "pod": "a"},
        {"namespace": "default", "pod": "b"}]
    with pytest.raises(ValueError):
        cli._parse_slice_pods(["ns1/"])
    with pytest.raises(ValueError):
        cli._parse_slice_pods(["/pod"])      # empty namespace


def test_slice_add_against_multinode(fake_host, tmp_path):
    from gpumounter_tpu.testing.sim import MultiNodeStack
    from gpumounter_tpu.utils.config import HostPaths
    hosts = []
    for i in range(2):
        root = tmp_path / f"host{i}"
        for d in ("dev", "proc", "sys/fs/cgroup"):
            (root / d).mkdir(parents=True)
        hosts.append(HostPaths(
            dev_root=str(root / "dev"), proc_root=str(root / "proc"),
            sys_root=str(root / "sys"),
            cgroup_root=str(root / "sys" / "fs" / "cgroup"),
            kubelet_socket=str(root / "pr" / "kubelet.sock")))
    stack = MultiNodeStack(hosts)
    try:
        rc, out = run_cli(
            stack.base, "slice", "add",
            "-p", "default/workload-0", "-p", "default/workload-1",
            "--tpus-per-host", "4")
        assert rc == 0 and "SUCCESS" in out
        rc, out = run_cli(
            stack.base, "slice", "remove",
            "-p", "default/workload-0", "-p", "default/workload-1")
        assert rc == 0 and "SUCCESS" in out
    finally:
        stack.close()


def test_node_inventory_command(live_stack):
    rig, base = live_stack
    rc, out = run_cli(base, "node", "node-a")
    assert rc == 0
    assert "4/4 chips free" in out
    run_cli(base, "add", "workload", "--tpus", "1")
    rc, out = run_cli(base, "node", "node-a")
    assert rc == 0 and "3/4 chips free" in out
    assert "tpu-pool/workload-slave-pod-" in out
    rc, out = run_cli(base, "node", "nope")
    assert rc == 1 and "NodeNotFound" in out and "None" not in out


def test_slice_remove_retry_converges(fake_host, tmp_path, monkeypatch):
    """A retried slice remove after a lost reply converges to SUCCESS
    (detach counts TPU_NOT_FOUND as done) — the CLI's retry of slice
    remove is safe even without add-style adoption machinery."""
    from gpumounter_tpu.testing.sim import MultiNodeStack
    from gpumounter_tpu.utils.config import HostPaths
    hosts = []
    for i in range(2):
        root = tmp_path / f"host{i}"
        for d in ("dev", "proc", "sys/fs/cgroup"):
            (root / d).mkdir(parents=True)
        hosts.append(HostPaths(
            dev_root=str(root / "dev"), proc_root=str(root / "proc"),
            sys_root=str(root / "sys"),
            cgroup_root=str(root / "sys" / "fs" / "cgroup"),
            kubelet_socket=str(root / "pr" / "kubelet.sock")))
    stack = MultiNodeStack(hosts)
    try:
        rc, _ = run_cli(stack.base, "slice", "add",
                        "-p", "default/workload-0", "-p",
                        "default/workload-1")
        assert rc == 0
        # first remove commits server-side but the CLI "loses" the reply:
        # simulate by retrying AFTER a successful remove
        rc, _ = run_cli(stack.base, "slice", "remove",
                        "-p", "default/workload-0", "-p",
                        "default/workload-1")
        assert rc == 0
        rc, out = run_cli(stack.base, "slice", "remove",
                          "-p", "default/workload-0", "-p",
                          "default/workload-1")
        assert rc == 0 and "SUCCESS" in out     # converged, not 409
    finally:
        stack.close()


def test_exposition_round_trip_registry_to_parser():
    """Both ends of the hand-rolled text format guard each other: a fully
    populated Registry must render text that cli._parse_exposition parses
    back into EVERY series with its exact value — histogram buckets,
    sums/counts, labeled counters, gauges, build_info included."""
    from gpumounter_tpu.utils.metrics import Registry
    reg = Registry()
    reg.attach_latency.observe(0.3)
    reg.attach_latency.observe(7.5)
    reg.detach_latency.observe(0.01)
    reg.attach_results.inc(result="SUCCESS")
    reg.attach_results.inc(2, result="EXCEPTION")
    reg.chips.set(3, state="free")
    reg.chips.set(1, state="allocated")
    reg.warm_pool_size.set(2, key="entire:4")
    reg.pool_refill_latency.observe(1.25)
    reg.attach_phase.observe(0.2, phase="allocate")
    reg.attach_phase.observe(0.05, phase="actuate")
    reg.detach_phase.observe(0.1, phase="cleanup")
    # exemplar-bearing series (ISSUE 7): the rid exemplar rides the
    # bucket line after ` # ` and must NOT disturb value parsing
    reg.gateway_requests.observe(0.4, route="addtpu",
                                 exemplar={"rid": "deadbeef0001"})
    reg.attach_latency.observe(0.31, exemplar={"rid": "deadbeef0002"})
    reg.k8s_latency.observe(0.02, verb="GET", resource="pods")
    reg.k8s_errors.inc(verb="LIST", resource="pods")
    # telemetry-plane families: lifecycle event counter, tenant-labeled
    # queue wait, SLO burn gauge, flight counters, fleet gauge
    reg.events_emitted.inc(kind="attach")
    reg.events_emitted.inc(3, kind="lease_record")
    reg.queue_wait.observe(2.5, tenant="teamA")
    reg.slo_burn_rate.set(1.25, tenant="teamA", slo="attach_success",
                          window="5m")
    reg.flight_dumps.inc(trigger="fast_burn")
    reg.fleet_nodes.set(3, state="fresh")
    # utilization-plane families (ISSUE 10): per-chip duty gauge,
    # per-tenant lease utilization + idle chips, device-open accounting
    reg.chip_duty_cycle.set(0.93, chip="0")
    reg.lease_utilization.set(0.45, tenant="teamA")
    reg.tenant_chips_idle.set(2, tenant="teamB")
    reg.device_opens.inc(tenant="teamA", outcome="attributed")
    reg.device_opens.inc(2, tenant="", outcome="unattributed")
    # topology-plane families (ISSUE 17): fragmentation score, per-node
    # free-block gauge, stranded chips, group contiguity, cross-shard
    # tenant rollup, defrag-candidate counter
    reg.fleet_fragmentation_score.set(0.62)
    reg.node_free_contiguous_chips.set(2, node="node-0")
    reg.stranded_chips.set(1)
    reg.slice_contiguity.set(1, group="g1")
    reg.tenant_chips_in_use_global.set(6, tenant="teamA")
    reg.defrag_candidates.inc(node="node-1")

    # classic exposition: NO exemplars (the ` # {...}` suffix is a parse
    # error for a real Prometheus scraping text/plain; version=0.0.4) —
    # they appear only in the negotiated OpenMetrics rendering
    plain = reg.render_text()
    assert " # {" not in plain and "deadbeef0001" not in plain
    text = reg.render_text(openmetrics=True)
    parsed = cli._parse_exposition(text)
    # the exemplars rendered (and will be stripped by the parser)
    assert 'deadbeef0001' in text and " # {" in text
    assert text.rstrip().endswith("# EOF")
    # OpenMetrics names counter FAMILIES without the _total suffix
    # (samples keep it); classic exposition keeps the historical
    # family name == sample name
    assert "# TYPE tpumounter_events counter" in text
    assert "# TYPE tpumounter_events_total counter" not in text
    assert "tpumounter_events_total{" in text     # samples unchanged
    assert "# TYPE tpumounter_events_total counter" in plain
    # both renderings parse to the same series values
    assert cli._parse_exposition(plain)[
        "tpumounter_gateway_request_seconds_bucket"] == parsed[
        "tpumounter_gateway_request_seconds_bucket"]

    reproduced = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        line = line.split(" # ", 1)[0].rstrip()   # exemplar-aware, like
        name = line.partition("{")[0].split()[0]  # the parser itself
        value = float(line.rsplit(" ", 1)[1])
        labels = {}
        if "{" in line:
            inner = line.partition("{")[2].rpartition("}")[0]
            for part in inner.split(","):
                k, _, v = part.partition("=")
                labels[k] = v.strip('"')
        key = tuple(sorted(labels.items()))
        assert name in parsed, line
        assert parsed[name].get(key) == value, line
        reproduced += 1
    # every rendered series came back out, and there were plenty of them
    assert reproduced == sum(len(s) for s in parsed.values())
    assert reproduced > 60
    # spot checks through the parser's own accessors
    assert cli._counter_total(parsed, "tpumounter_attach_total",
                              result="EXCEPTION") == 2
    p50 = cli._histogram_quantile(parsed, "tpumounter_attach_phase_seconds",
                                  0.5, phase="allocate")
    assert p50 is not None and 0 < p50 <= 0.25
    assert parsed["tpumounter_build_info"]
    # telemetry-plane round trips
    assert cli._counter_total(parsed, "tpumounter_events_total") == 4
    assert parsed["tpumounter_slo_burn_rate"][
        (("slo", "attach_success"), ("tenant", "teamA"),
         ("window", "5m"))] == 1.25
    assert cli._counter_total(parsed, "tpumounter_flight_dumps_total",
                              trigger="fast_burn") == 1
    assert cli._histogram_quantile(
        parsed, "tpumounter_queue_wait_seconds", 0.5,
        tenant="teamA") is not None
    # the exemplar-bearing bucket parsed to its exact cumulative count
    assert parsed["tpumounter_gateway_request_seconds_bucket"][
        (("le", "0.5"), ("route", "addtpu"))] == 1
    # topology-plane round trips
    assert parsed["tpumounter_fleet_fragmentation_score"][()] == 0.62
    assert parsed["tpumounter_slice_contiguity"][(("group", "g1"),)] == 1
    assert cli._counter_total(parsed,
                              "tpumounter_defrag_candidates_total",
                              node="node-1") == 1


def test_doctor_reports_version_and_slowest_trace(live_stack):
    """Satellites: doctor surfaces the scraped tpumounter_build_info
    version, and the slowest stored trace with its dominant span."""
    import gpumounter_tpu
    _, base = live_stack
    run_cli(base, "add", "workload", "--tpus", "1")
    rc, out = run_cli(base, "doctor")
    assert f"target version {gpumounter_tpu.__version__}" in out
    assert "slowest stored trace" in out
    assert "dominant span" in out
    assert "tpumounterctl trace" in out


def test_doctor_healthy_stack(live_stack):
    """The global REGISTRY accumulates across the whole test process, so
    expectations derive from its current state instead of assuming zeros
    (earlier test files legitimately record EXCEPTIONs/rollbacks)."""
    from gpumounter_tpu.utils.metrics import REGISTRY
    _, base = live_stack
    run_cli(base, "add", "workload", "--tpus", "2")
    dirty = (REGISTRY.attach_results.value(result="EXCEPTION")
             + REGISTRY.detach_results.value(result="EXCEPTION")
             + REGISTRY.attach_results.value(result="slice_ERROR")
             + REGISTRY.detach_results.value(result="slice_ERROR")
             + REGISTRY.attach_phase.count(phase="rollback")) > 0
    rc, out = run_cli(base, "doctor", "--node", "node-a")
    assert rc == (1 if dirty else 0), out
    assert "master reachable" in out
    assert "worker-local" in out
    assert "attach rollbacks:" in out
    assert "attach p95" in out
    assert "chips free" in out
    # --json emits the machine-readable check list like other subcommands
    rc, out = run_cli(base, "--json", "doctor")
    assert rc == (1 if dirty else 0)
    payload = json.loads(out)
    assert payload["worst"] == ("warn" if dirty else "ok")
    assert any("master reachable" in c["message"]
               for c in payload["checks"])


def test_doctor_flags_node_exhaustion_and_bad_node(live_stack):
    _, base = live_stack
    run_cli(base, "add", "workload", "--tpus", "4", "--entire")
    rc, out = run_cli(base, "doctor", "--node", "node-a")
    assert rc == 1                       # 0 free chips -> WARN
    assert "0/4 chips free" in out
    rc, out = run_cli(base, "doctor", "--node", "ghost-node")
    assert rc == cli.EXIT_DOCTOR_CRIT    # unknown node -> CRIT (12, not
    assert "NodeNotFound" in out         # argparse's 2)


def test_doctor_unreachable_master_is_crit():
    rc, out = run_cli("http://127.0.0.1:1", "--timeout", "1", "doctor")
    assert rc == cli.EXIT_DOCTOR_CRIT
    assert "master unreachable" in out


def test_histogram_quantile_estimator():
    metrics = cli._parse_exposition("\n".join([
        'h_bucket{le="0.1"} 50',
        'h_bucket{le="1"} 90',
        'h_bucket{le="+Inf"} 100',
        "h_sum 40",
        "h_count 100",
    ]))
    p50 = cli._histogram_quantile(metrics, "h", 0.50)
    assert p50 == pytest.approx(0.1)     # 50th obs sits at the 0.1 bound
    p95 = cli._histogram_quantile(metrics, "h", 0.95)
    assert 0.1 < p95 <= 1.0              # interpolated inside (0.1, 1]
    # quantile beyond the last finite bucket clamps to it
    p999 = cli._histogram_quantile(metrics, "h", 0.999)
    assert p999 == pytest.approx(1.0)
    assert cli._histogram_quantile(metrics, "absent", 0.5) is None


def test_parse_exposition_labels_and_values():
    m = cli._parse_exposition("\n".join([
        "# HELP x help",
        "# TYPE x counter",
        'x{result="SUCCESS"} 3',
        'x{result="EXCEPTION"} 1',
        "y 2.5",
    ]))
    assert cli._counter_total(m, "x") == 4
    assert cli._counter_total(m, "x", result="EXCEPTION") == 1
    assert m["y"][()] == 2.5


def test_doctor_lifetime_counters_warn_not_crit(live_stack):
    """A historical exception must not page forever: lifetime totals WARN;
    only windowed (current) activity may CRIT."""
    from gpumounter_tpu.utils.metrics import REGISTRY
    _, base = live_stack
    expected = int(REGISTRY.attach_results.value(result="EXCEPTION")
                   + REGISTRY.detach_results.value(result="EXCEPTION")) + 1
    REGISTRY.attach_results.inc(result="EXCEPTION")
    rc, out = run_cli(base, "doctor")
    assert rc == 1, out                  # WARN, not EXIT_DOCTOR_CRIT
    assert f"{expected} worker-local" in out
    assert "lifetime" in out
    # windowed: no NEW exceptions inside the window -> healthy
    rc, out = run_cli(base, "doctor", "--window", "0.2")
    assert rc == 0, out
    assert "exceptions: 0 worker-local" in out
    assert "in the last 0.2s" in out


def test_parse_exposition_trailing_timestamp():
    """Standard exposition lines may carry a trailing timestamp_ms; the
    sample value is the first token after the name/labels, not the last."""
    m = cli._parse_exposition("\n".join([
        'x{result="SUCCESS"} 3 1712345678901',
        "y 2.5 1712345678901",
        "z 7",
    ]))
    assert m["x"][(("result", "SUCCESS"),)] == 3
    assert m["y"][()] == 2.5
    assert m["z"][()] == 7


def test_doctor_window_counter_reset_falls_back_to_lifetime(monkeypatch):
    """A process restart between the two scrapes makes the second sample
    LOWER: the deltas are meaningless, so doctor must say 'counter reset'
    and judge lifetime totals (WARN ceiling) instead of printing negative
    counts or paging CRIT for a restart."""
    scrapes = ['tpumounter_attach_total{result="EXCEPTION"} 5\n',
               'tpumounter_attach_total{result="EXCEPTION"} 1\n']

    def fake_fetch(master, path, timeout):
        if path == "/healthz":
            return '{"status": "ok"}'
        return scrapes.pop(0) if len(scrapes) > 1 else scrapes[0]

    monkeypatch.setattr(cli, "_fetch_text", fake_fetch)
    monkeypatch.setattr(cli.time, "sleep", lambda s: None)
    rc, out = run_cli("http://unused", "doctor", "--window", "5")
    assert rc == 1, out                         # WARN, never CRIT
    assert "counter reset" in out
    assert "-4" not in out                      # the raw delta, never shown
    assert "exceptions: 5" in out               # lifetime figure instead
    assert "lifetime" in out


def test_doctor_windowed_p95_diffs_histogram(monkeypatch):
    """--window judges the p95 of attaches INSIDE the window (bucket
    deltas), not the lifetime histogram — and says which scope it used."""
    first = "\n".join([
        'tpumounter_attach_seconds_bucket{le="0.1"} 0',
        'tpumounter_attach_seconds_bucket{le="30"} 10',
        'tpumounter_attach_seconds_bucket{le="+Inf"} 10',
        "tpumounter_attach_seconds_count 10",
    ])
    second = "\n".join([
        'tpumounter_attach_seconds_bucket{le="0.1"} 2',
        'tpumounter_attach_seconds_bucket{le="30"} 12',
        'tpumounter_attach_seconds_bucket{le="+Inf"} 12',
        "tpumounter_attach_seconds_count 12",
    ])
    scrapes = [first, second]

    def fake_fetch(master, path, timeout):
        if path == "/healthz":
            return '{"status": "ok"}'
        return scrapes.pop(0) if len(scrapes) > 1 else scrapes[0]

    monkeypatch.setattr(cli, "_fetch_text", fake_fetch)
    monkeypatch.setattr(cli.time, "sleep", lambda s: None)
    rc, out = run_cli("http://unused", "doctor", "--window", "5")
    # the 10 lifetime ~30s attaches would WARN; the 2 in-window attaches
    # are fast, so the windowed check is healthy and scoped
    assert rc == 0, out
    assert "over 2 attach(es)" in out
    assert "in the last 5s" in out

    # lifetime mode still reports, but now says it is a lifetime figure
    scrapes = [first]
    rc, out = run_cli("http://unused", "doctor")
    assert rc == 1, out                 # p95 ~30s over 10 attaches: WARN
    assert "over 10 attach(es)" in out
    assert "lifetime" in out


def test_doctor_window_gauge_decrease_is_not_a_counter_reset(monkeypatch):
    """Gauges go down in normal operation (chips freed, warm pod adopted);
    only counter-semantics families may trip the reset fallback."""
    scrapes = ["\n".join(['tpumounter_node_chips{state="allocated"} 4',
                          "tpumounter_attach_total 7"]),
               "\n".join(['tpumounter_node_chips{state="allocated"} 0',
                          "tpumounter_attach_total 8"])]

    def fake_fetch(master, path, timeout):
        if path == "/healthz":
            return '{"status": "ok"}'
        return scrapes.pop(0) if len(scrapes) > 1 else scrapes[0]

    monkeypatch.setattr(cli, "_fetch_text", fake_fetch)
    monkeypatch.setattr(cli.time, "sleep", lambda s: None)
    rc, out = run_cli("http://unused", "doctor", "--window", "5")
    assert rc == 0, out
    assert "counter reset" not in out
    assert "in the last 5s" in out              # windowed judgement kept


def test_doctor_reports_open_circuit_as_crit(monkeypatch):
    """An open breaker is CURRENT state (the target is failing fast right
    now), so it may page — unlike cumulative counters."""
    metrics = "\n".join([
        'tpumounter_circuit_state{target="10.0.0.5:1200"} 2',
        'tpumounter_circuit_state{target="10.0.0.6:1200"} 0',
        "tpumounter_retry_attempts_total 12",
    ])

    def fake_fetch(master, path, timeout):
        if path == "/healthz":
            return '{"status": "ok"}'
        if path == "/journalz":
            raise cli.TransportError("no journal here")
        return metrics

    monkeypatch.setattr(cli, "_fetch_text", fake_fetch)
    rc, out = run_cli("http://unused", "doctor")
    assert rc == cli.EXIT_DOCTOR_CRIT, out
    assert "circuit OPEN for 10.0.0.5:1200" in out
    assert "retries absorbed: 12" in out


def test_doctor_reports_closed_circuits_and_journal_backlog(monkeypatch):
    """Healthy circuits are an OK line; a worker /journalz backlog WARNs
    (incomplete actuation state is sitting on the node)."""
    metrics = 'tpumounter_circuit_state{target="10.0.0.5:1200"} 0\n'

    def fake_fetch(master, path, timeout):
        if path == "/healthz":
            return "ok"                          # worker-style healthz
        if path == "/journalz":
            return json.dumps({"backlog": 2, "incomplete": [],
                               "records": [], "replays": {}})
        return metrics

    monkeypatch.setattr(cli, "_fetch_text", fake_fetch)
    rc, out = run_cli("http://unused", "doctor")
    assert rc == 1, out
    assert "all 1 circuit(s) closed" in out
    assert "attach-journal backlog: 2" in out


def test_doctor_windowed_retry_activity_warns(monkeypatch):
    """Retries inside the window mean the control plane is absorbing
    faults RIGHT NOW — warn; the same lifetime total alone is just
    history."""
    scrapes = ["tpumounter_retry_attempts_total 100\n",
               "tpumounter_retry_attempts_total 104\n"]

    def fake_fetch(master, path, timeout):
        if path == "/healthz":
            return '{"status": "ok"}'
        if path == "/journalz":
            raise cli.TransportError("no journal here")
        return scrapes.pop(0) if len(scrapes) > 1 else scrapes[0]

    monkeypatch.setattr(cli, "_fetch_text", fake_fetch)
    monkeypatch.setattr(cli.time, "sleep", lambda s: None)
    rc, out = run_cli("http://unused", "doctor", "--window", "5")
    assert rc == 1, out
    assert "retries absorbed: 4" in out
    assert "in the last 5s" in out

    rc, out = run_cli("http://unused", "doctor")
    assert rc == 0, out
    assert "retries absorbed: 104" in out
    assert "lifetime" in out


# -- cachez: shared-informer cache health (ISSUE 4) ----------------------------

def test_cachez_against_informer_worker(fake_host):
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True,
                                informer=True))
    try:
        worker = f"http://127.0.0.1:{stack.health_server.server_port}"
        rc, out = run_cli(worker, "cachez")
        assert rc == 0
        assert "scope tpu-pool/*" in out
        assert "staleness" in out and "watch restart" in out

        rc, out = run_cli(worker, "--json", "cachez")
        payload = json.loads(out)
        assert payload["enabled"] is True
        assert payload["scopes"][0]["namespace"] == "tpu-pool"
    finally:
        stack.close()


def test_cachez_against_informerless_worker(fake_host):
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True))
    try:
        worker = f"http://127.0.0.1:{stack.health_server.server_port}"
        rc, out = run_cli(worker, "cachez")
        assert rc == 0
        assert "disabled" in out
    finally:
        stack.close()


def test_renew_cli_and_doctor_broker_checks(fake_host):
    """Broker satellites end-to-end over HTTP: `tpumounterctl renew`
    extends a live lease (404 + typed exit code for unknown ones), and
    doctor reports queue depth / quota pressure from the new metric
    families — a tenant at 100% of quota WARNs."""
    from gpumounter_tpu.master.admission import BrokerConfig
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True),
                      broker_config=BrokerConfig(quotas={"*": 2},
                                                 lease_ttl_s=600.0))
    try:
        base = stack.base
        rc, out = run_cli(base, "add", "workload", "--tpus", "2")
        assert rc == 0 and "SUCCESS" in out
        rc, out = run_cli(base, "renew", "workload", "--ttl", "1200")
        assert rc == 0 and "lease extended" in out
        rc, out = run_cli(base, "--json", "renew", "workload")
        payload = json.loads(out)
        assert payload["result"] == "SUCCESS"
        assert payload["lease"]["renewals"] == 2
        rc, out = run_cli(base, "renew", "ghost")
        assert rc == cli.EXIT_CODES["LeaseNotFound"]
        # over-quota attach surfaces the typed 429 exit code
        rc, out = run_cli(base, "--json", "add", "workload", "--tpus", "1")
        assert rc == cli.EXIT_CODES["QuotaExceeded"]
        assert json.loads(out)["result"] == "QuotaExceeded"
        # doctor: tenant 'default' sits at 2/2 chips => >90% quota WARN,
        # queue is empty => reported, not warned
        stack.gateway.broker.tick()      # refresh the broker gauges now
        rc, out = run_cli(base, "doctor")
        assert rc == 1
        assert ">90% quota" in out
        assert "default (2/2 chips)" in out
        assert "attach queue empty" in out
    finally:
        stack.close()


def test_doctor_reports_informer_cache_health(fake_host):
    """doctor pointed at a worker's health port surfaces the cache check
    (fresh => OK; the WARN path is driven by staleness over threshold)."""
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True,
                                informer=True))
    try:
        worker = f"http://127.0.0.1:{stack.health_server.server_port}"
        rc, out = run_cli(worker, "doctor")
        assert "informer cache fresh" in out
        assert rc in (0, 1)
    finally:
        stack.close()


def test_doctor_warns_on_stale_cache(monkeypatch):
    """A /cachez payload whose scope staleness exceeds the threshold WARNs
    (exit 1), naming the staleness."""
    payloads = {
        "/healthz": "ok",
        "/metrics": "",
        "/cachez": json.dumps({
            "enabled": True, "hits": 5, "misses": 1, "hit_ratio": 0.83,
            "fence_timeout_s": 2.0,
            "scopes": [{"namespace": "tpu-pool", "selector": None,
                        "pods": 3, "resource_version": "9",
                        "seeded": True, "running": True,
                        "staleness_s": 600.0, "watch_restarts": 7,
                        "events_seen": 42}]}),
    }
    monkeypatch.setattr(
        cli, "_fetch_text",
        lambda master, path, timeout: payloads.get(path.split("?")[0], ""))
    rc, out = run_cli("http://unused", "doctor")
    assert rc == 1
    assert "informer cache stale" in out and "600s" in out


# -- agentz: resident actuation agent health (ISSUE 6) -------------------------

def test_agentz_against_agent_worker(fake_host):
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True,
                                informer=True, agent=True,
                                actuator="procroot"))
    try:
        rig = stack.rig
        assert rig.service.add_tpu("workload", "default", 4, True,
                                   request_id="agentz-test").result.name \
            == "SUCCESS"
        worker = f"http://127.0.0.1:{stack.health_server.server_port}"
        from gpumounter_tpu.actuation.agent import _fallback_total
        rc, out = run_cli(worker, "agentz")
        # counters are process-global: an earlier test exercising the
        # fallback seam makes agentz exit non-zero by design
        assert rc == (0 if _fallback_total() == 0 else 1), out
        assert "mode=procroot" in out and "executor=alive" in out
        assert "ns fd pid" in out

        rc, out = run_cli(worker, "--json", "agentz")
        payload = json.loads(out)
        assert payload["enabled"] is True
        assert payload["counters"]["batches"] >= 1
    finally:
        stack.close()


def test_agentz_against_agentless_worker(fake_host):
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True))
    try:
        worker = f"http://127.0.0.1:{stack.health_server.server_port}"
        rc, out = run_cli(worker, "agentz")
        assert rc == 0
        assert "disabled" in out
    finally:
        stack.close()


def test_agentz_flags_fallbacks(fake_host):
    """A non-zero fallback count exits non-zero with a warning — the
    resident path is degrading and someone should look."""
    from gpumounter_tpu.actuation.agent import AgentFault
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True,
                                informer=True, agent=True,
                                actuator="procroot"))
    try:
        # force one fallback: a container the agent cannot anchor
        stack.rig.actuator.apply_device_nodes(31337,
                                              [("/dev/accel9", 1, 2)], [])
        worker = f"http://127.0.0.1:{stack.health_server.server_port}"
        rc, out = run_cli(worker, "agentz")
        assert rc != 0
        assert "fallback" in out and "WARNING" in out
    finally:
        stack.close()


def test_doctor_warns_on_agent_fallbacks(fake_host):
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True,
                                informer=True, agent=True,
                                actuator="procroot"))
    try:
        rig = stack.rig
        assert rig.service.add_tpu("workload", "default", 4, True,
                                   request_id="doctor-agent").result.name \
            == "SUCCESS"
        worker = f"http://127.0.0.1:{stack.health_server.server_port}"
        from gpumounter_tpu.actuation.agent import _fallback_total
        rc, out = run_cli(worker, "doctor")
        if _fallback_total() == 0:
            # (counters are process-global; an earlier test in this run
            # may already have exercised the fallback seam)
            assert "actuation agent healthy" in out, out
        # now degrade it and expect the WARN
        rig.actuator.apply_device_nodes(31337, [("/dev/accel9", 1, 2)], [])
        rc, out = run_cli(worker, "doctor")
        assert "actuation agent fallbacks" in out, out
        assert rc != 0
    finally:
        stack.close()
