"""Device model + enumerator tests (ref test analog: nvml_test.go, but
hermetic — no live hardware required; SURVEY.md §4)."""

import os

from gpumounter_tpu.device.enumerator import PyEnumerator, read_proc_devices
from gpumounter_tpu.device.fake import FakeEnumerator, make_chips
from gpumounter_tpu.device.model import DeviceState, TPUChip


def test_chip_reset_state():
    chip = TPUChip(index=0, device_path="/dev/accel0", major=120, minor=0,
                   uuid="0", state=DeviceState.ALLOCATED,
                   pod_name="p", namespace="ns")
    chip.reset_state()
    assert chip.state is DeviceState.FREE
    assert chip.pod_name == "" and chip.namespace == ""


def test_chip_str_is_json():
    import json
    chip = make_chips(1)[0]
    parsed = json.loads(str(chip))
    assert parsed["device_path"] == "/dev/accel0"
    assert parsed["major"] == 120


def test_py_enumerator_fixture_accel_devices(fake_host):
    for i in range(4):
        path = os.path.join(fake_host.dev_root, f"accel{i}")
        with open(path, "w"):
            pass
        with open(path + ".majmin", "w") as f:
            f.write(f"120:{i}")
    # distractor entries must be ignored
    open(os.path.join(fake_host.dev_root, "null"), "w").close()
    os.mkdir(os.path.join(fake_host.dev_root, "acceldir"))

    chips = PyEnumerator(fake_host, allow_fake=True).enumerate()
    assert [c.index for c in chips] == [0, 1, 2, 3]
    assert all(c.major == 120 for c in chips)
    assert [c.minor for c in chips] == [0, 1, 2, 3]
    assert chips[0].device_path.endswith("/accel0")
    assert chips[0].uuid == "0"


def test_py_enumerator_requires_char_device_without_fake_flag(fake_host):
    open(os.path.join(fake_host.dev_root, "accel0"), "w").close()
    assert PyEnumerator(fake_host, allow_fake=False).enumerate() == []


def test_py_enumerator_vfio_fallback(fake_host):
    vfio = os.path.join(fake_host.dev_root, "vfio")
    os.mkdir(vfio)
    for name in ("0", "1", "vfio"):
        open(os.path.join(vfio, name), "w").close()
    with open(os.path.join(vfio, "vfio.majmin"), "w") as f:
        f.write("10:196")
    chips = PyEnumerator(fake_host, allow_fake=True).enumerate()
    assert len(chips) == 2
    assert chips[0].device_path.endswith("/vfio/0")
    assert chips[0].container_path == "/dev/vfio/0"
    for c in chips:
        (comp,) = c.companions
        assert comp.host_path.endswith("/vfio/vfio")
        assert comp.container_path == "/dev/vfio/vfio"
        assert (comp.major, comp.minor) == (10, 196)


def test_py_enumerator_pci_address_from_sysfs(fake_host):
    accel_cls = os.path.join(fake_host.sys_root, "class", "accel", "accel0")
    os.makedirs(accel_cls)
    pci_dir = os.path.join(fake_host.sys_root, "devices", "pci0", "0000:05:00.0")
    os.makedirs(pci_dir)
    os.symlink(pci_dir, os.path.join(accel_cls, "device"))
    path = os.path.join(fake_host.dev_root, "accel0")
    open(path, "w").close()
    chips = PyEnumerator(fake_host, allow_fake=True).enumerate()
    assert chips[0].pci_address == "0000:05:00.0"


def test_read_proc_devices(fake_host):
    with open(os.path.join(fake_host.proc_root, "devices"), "w") as f:
        f.write("Character devices:\n  1 mem\n120 accel\n511 vfio\n\n"
                "Block devices:\n  8 sd\n")
    majors = read_proc_devices(fake_host.proc_root)
    assert majors["accel"] == 120
    assert majors["vfio"] == 511
    assert "sd" not in majors


def test_busy_detection_proc_fd_scan(fake_host):
    dev = os.path.join(fake_host.dev_root, "accel0")
    open(dev, "w").close()
    # pid 100 holds the device open; pid 200 holds something else; 300 is gone
    for pid, target in ((100, dev),
                        (200, os.path.join(fake_host.dev_root, "null"))):
        fd_dir = os.path.join(fake_host.proc_root, str(pid), "fd")
        os.makedirs(fd_dir)
        os.symlink(target, os.path.join(fd_dir, "3"))
    enum = PyEnumerator(fake_host, allow_fake=True)
    assert enum.device_open_pids([100, 200, 300], [dev]) == [100]


def test_fake_enumerator_busy():
    fake = FakeEnumerator(busy_pids={"/dev/accel1": [42]})
    assert fake.device_open_pids([41, 42], ["/dev/accel1"]) == [42]
    assert fake.device_open_pids([41, 42], ["/dev/accel0"]) == []
    assert len(fake.enumerate()) == 4


def test_py_enumerator_numeric_order_10_plus(fake_host):
    # lexicographic sort would yield [0, 1, 10, 11, 2, ...]
    for i in range(12):
        path = os.path.join(fake_host.dev_root, f"accel{i}")
        open(path, "w").close()
    chips = PyEnumerator(fake_host, allow_fake=True).enumerate()
    assert [c.index for c in chips] == list(range(12))
