"""Intent-store suite (master/store.py): byte-identical record
round-trips through the CAS write path, replica conflict handling,
fencing, torn-record degradation to cluster re-derivation, the dirty
queue, and the defaults-off pin (no HA knobs ⇒ zero configmap traffic —
exactly PR 7 semantics)."""

import json

import pytest

from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.master.admission import AttachBroker, BrokerConfig
from gpumounter_tpu.master.election import NullElection
from gpumounter_tpu.master.shardring import HAConfig, ShardRing
from gpumounter_tpu.master.store import (IntentStore, LeaseRecord,
                                         WaiterRecord)
from gpumounter_tpu.testing.chaos import Fault, FaultInjector
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import Settings
from gpumounter_tpu.utils.errors import StoreFencedError
from gpumounter_tpu.utils.metrics import REGISTRY


NS = consts.DEFAULT_POOL_NAMESPACE


def make_store(kube=None, shards=1, election=None):
    kube = kube or FakeKubeClient()
    return kube, IntentStore(kube, ShardRing(shards), NS,
                             election=election)


def lease_record(**over):
    fields = dict(namespace="default", pod="workload", tenant="teamA",
                  priority="high", chips=3, uuids=["0", "2", "7"],
                  node="node-a", rid="rid-1", created_unix=1234.5,
                  expires_unix=99999.25, renewals=2)
    fields.update(over)
    return LeaseRecord(**fields)


def waiter_record(**over):
    fields = dict(rid="w-rid-1", namespace="default", pod="contender",
                  tenant="teamB", priority="normal", chips=2,
                  node="node-a", entire=True, enqueued_unix=1000.0,
                  deadline_unix=1060.0)
    fields.update(over)
    return WaiterRecord(**fields)


def raw_annotations(kube, shard=0):
    cm = kube.get_config_map(NS, f"{consts.STORE_CONFIGMAP_PREFIX}{shard}")
    return dict(cm["metadata"].get("annotations") or {})


# -- round trips ---------------------------------------------------------------

def test_lease_record_survives_cas_write_byte_identically():
    kube, store = make_store()
    record = lease_record()
    original = record.to_json()
    assert store.put_lease(record)
    # the persisted annotation IS the canonical serialization
    assert raw_annotations(kube)[record.annotation_key] == original
    leases, waiters, torn = store.rehydrate(0)
    assert torn == 0 and waiters == []
    assert len(leases) == 1
    assert leases[0] == record                      # field-identical
    assert leases[0].to_json() == original          # byte-identical

    # and the record materialises back into a working Lease
    lease = leases[0].to_lease()
    assert lease.key == ("default", "workload")
    assert lease.uuids == {"0", "2", "7"}
    assert lease.tenant == "teamA" and lease.priority == "high"
    assert LeaseRecord.from_lease(lease).uuids == ["0", "2", "7"]


def test_waiter_record_survives_cas_write_byte_identically():
    kube, store = make_store()
    record = waiter_record()
    original = record.to_json()
    assert store.put_waiter(record)
    assert raw_annotations(kube)[record.annotation_key] == original
    leases, waiters, torn = store.rehydrate(0)
    assert torn == 0 and leases == []
    assert waiters == [record]
    assert waiters[0].to_json() == original
    assert waiters[0].entire is True                # the re-run flag


def test_eternal_lease_round_trips_none_expiry():
    kube, store = make_store()
    record = lease_record(expires_unix=None)
    assert store.put_lease(record)
    leases, _, _ = store.rehydrate(0)
    assert leases[0].expires_unix is None
    assert leases[0].to_lease().expires_at is None


def test_delete_removes_the_record():
    kube, store = make_store()
    record = lease_record()
    wrec = waiter_record()
    store.put_lease(record)
    store.put_waiter(wrec)
    assert store.delete_lease("default", "workload")
    assert store.delete_waiter("default", wrec.rid)
    leases, waiters, _ = store.rehydrate(0)
    assert leases == [] and waiters == []


# -- CAS between replicas ------------------------------------------------------

def test_concurrent_replicas_conflict_and_both_land():
    kube = FakeKubeClient()
    _, store_a = make_store(kube)
    _, store_b = make_store(kube)
    before = REGISTRY.store_cas.value(op="put", outcome="conflict")
    assert store_a.put_lease(lease_record(pod="pod-a"))
    # B writes through a fresh read; A's cached resourceVersion is now
    # stale, so A's next write LOSES its first CAS and must re-read
    assert store_b.put_lease(lease_record(pod="pod-b"))
    assert store_a.put_lease(lease_record(pod="pod-c"))
    leases, _, torn = store_a.rehydrate(0)
    assert torn == 0
    assert {r.pod for r in leases} == {"pod-a", "pod-b", "pod-c"}
    assert REGISTRY.store_cas.value(op="put",
                                    outcome="conflict") > before


def test_create_race_one_winner_both_records_survive():
    kube = FakeKubeClient()
    _, store_a = make_store(kube)
    _, store_b = make_store(kube)
    # neither has observed the (absent) map: both take the create path;
    # the loser's 409 degrades to patch-and-retry
    assert store_a.put_lease(lease_record(pod="pod-a"))
    assert store_b.put_lease(lease_record(pod="pod-b"))
    leases, _, _ = store_b.rehydrate(0)
    assert {r.pod for r in leases} == {"pod-a", "pod-b"}


# -- fencing -------------------------------------------------------------------

class _StubElection:
    enabled = True

    def __init__(self, token):
        self._token = token

    def token(self, shard):
        return self._token


def test_deposed_writer_is_fenced():
    kube = FakeKubeClient()
    _, old_leader = make_store(kube, election=_StubElection(1))
    _, new_leader = make_store(kube, election=_StubElection(2))
    assert old_leader.put_lease(lease_record(pod="pod-a"))
    assert new_leader.put_lease(lease_record(pod="pod-b"))   # fence -> 2
    with pytest.raises(StoreFencedError) as err:
        old_leader.put_lease(lease_record(pod="pod-c"))
    assert err.value.token == 1 and err.value.fence == 2
    # the deposed replica wrote NOTHING
    leases, _, _ = new_leader.rehydrate(0)
    assert {r.pod for r in leases} == {"pod-a", "pod-b"}


# -- torn records --------------------------------------------------------------

def _slave_pod(name, owner, owner_ns="default", chips=2):
    return {
        "metadata": {
            "name": name, "namespace": NS,
            "labels": {
                consts.SLAVE_POD_LABEL_KEY: consts.SLAVE_POD_LABEL_VALUE,
                consts.OWNER_POD_LABEL_KEY: owner,
                consts.OWNER_NAMESPACE_LABEL_KEY: owner_ns,
            }},
        "spec": {"containers": [{
            "name": "c",
            "resources": {"limits": {
                consts.TPU_RESOURCE_NAME: str(chips)}}}]},
        "status": {"phase": "Running"},
    }


def test_torn_record_is_dropped_and_counted():
    kube, store = make_store()
    store.put_lease(lease_record(pod="good"))
    # crash mid-write: the annotation exists but holds half a record
    good = lease_record(pod="good")
    torn_key = consts.STORE_LEASE_ANNOTATION_PREFIX + "deadbeefdeadbeef"
    kube.patch_config_map(
        NS, store.cm_name(0),
        {"metadata": {"annotations": {
            torn_key: '{"namespace": "default", "pod": "torn-vic'}}})
    leases, _, torn = store.rehydrate(0)
    assert torn == 1
    assert [r.pod for r in leases] == ["good"]
    assert good.annotation_key in raw_annotations(kube)


def test_torn_lease_degrades_to_cluster_rederivation():
    """A broker whose store record for an attachment is torn still
    recovers the lease — from slave-pod ground truth — and re-syncs the
    store, so the NEXT failover rehydrates a whole record again."""
    kube = FakeKubeClient()
    kube.put_pod(_slave_pod("victim-slave-pod-1", "victim", chips=2))
    _, store = make_store(kube)
    torn_key = (consts.STORE_LEASE_ANNOTATION_PREFIX
                + "feedfacefeedface")
    # the torn write happened before the crash...
    kube.create_config_map(NS, {
        "metadata": {"name": store.cm_name(0),
                     "annotations": {torn_key: '{"namespace": "defau'}}})
    broker = AttachBroker(kube, BrokerConfig())
    broker.bind_ha(store, store.ring, NullElection(1))
    broker.ensure_rederived()
    # ...the replacement replica re-derived the lease from the cluster
    leases = broker.leases.leases()
    assert [(le.namespace, le.pod, le.chips) for le in leases] == \
        [("default", "victim", 2)]
    # and wrote it through, so the store is whole again
    records, _, _ = store.rehydrate(0)
    assert [(r.namespace, r.pod, r.chips) for r in records] == \
        [("default", "victim", 2)]


# -- dirty queue ---------------------------------------------------------------

def test_failed_write_parks_dirty_and_flushes():
    kube, store = make_store()
    store.put_lease(lease_record(pod="seed"))    # map exists
    injector = FaultInjector([Fault(op="PATCH", resource="configmaps",
                                    status=500, times=50)])
    kube.faults = injector
    assert store.put_lease(lease_record(pod="parked")) is False
    assert store.lag_s() > 0.0
    assert store.snapshot()["dirty"] == 1
    kube.faults = None
    assert store.flush_dirty() == 1
    assert store.lag_s() == 0.0
    leases, _, _ = store.rehydrate(0)
    assert {r.pod for r in leases} == {"seed", "parked"}


# -- defaults-off pin ----------------------------------------------------------

def test_defaults_are_single_master_pr7_semantics():
    settings = Settings()
    ha = HAConfig.from_settings(settings)
    assert not ha.enabled
    assert ha.shards == 1 and not ha.election and not ha.store
    env_ha = HAConfig.from_settings(Settings.from_env({}))
    assert not env_ha.enabled


def test_broker_without_ha_never_touches_configmaps():
    kube = FakeKubeClient()
    kube.put_pod(_slave_pod("w-slave-pod-1", "workload", chips=1))
    broker = AttachBroker(kube, BrokerConfig())
    broker.ensure_rederived()
    broker.leases.record("default", "workload", "default", "normal",
                         ["0"], node="node-a", rid="r1", ttl_s=0.0)
    broker.leases.release("default", "workload")
    broker.tick()
    assert kube.cm_calls == 0


def test_shard_ring_is_stable_and_uniformish():
    ring = ShardRing(4)
    assert ring.shard_of("default") == ring.shard_of("default")
    spread = {ring.shard_of(f"ns-{i}") for i in range(64)}
    assert spread == {0, 1, 2, 3}
    assert ShardRing(1).shard_of("anything") == 0


def test_parked_put_never_resurrects_a_newer_live_delete():
    """Review fix: a put that parked dirty during an outage must not be
    replayed over the SAME key's newer live delete — last writer wins
    per key, whether the later write lands live or parks too."""
    kube, store = make_store()
    store.put_lease(lease_record(pod="seed"))    # map exists
    kube.faults = FaultInjector([Fault(op="PATCH", resource="configmaps",
                                       status=500, times=50)])
    assert store.put_lease(lease_record(pod="ghost")) is False
    assert store.snapshot()["dirty"] == 1
    kube.faults = None
    # apiserver recovers; the client detaches: the delete lands LIVE
    assert store.delete_lease("default", "ghost") is True
    # the parked put is now stale and must be gone — flushing replays
    # nothing and the record stays deleted
    assert store.snapshot()["dirty"] == 0
    assert store.flush_dirty() == 0
    leases, _, _ = store.rehydrate(0)
    assert {r.pod for r in leases} == {"seed"}, \
        "a stale parked put resurrected a deleted lease"


def test_dirty_queue_keeps_one_mutation_per_key_newest_value():
    """Two failed writes for one key collapse to ONE parked mutation
    carrying the NEWEST value (and the oldest timestamp, for lag)."""
    kube, store = make_store()
    store.put_lease(lease_record(pod="seed"))
    kube.faults = FaultInjector([Fault(op="PATCH", resource="configmaps",
                                       status=500, times=50)])
    assert store.put_lease(lease_record(pod="p", chips=1,
                                        uuids=["0"])) is False
    assert store.put_lease(lease_record(pod="p", chips=3,
                                        uuids=["0", "1", "2"])) is False
    assert store.snapshot()["dirty"] == 1
    kube.faults = None
    assert store.flush_dirty() == 1
    leases, _, _ = store.rehydrate(0)
    by_pod = {r.pod: r for r in leases}
    assert by_pod["p"].chips == 3, "the stale parked value won"


class _DecayedElection:
    """Election whose token just expired: enabled, owns nothing."""

    enabled = True

    def __init__(self, shards=1):
        self.shards = shards

    def token(self, shard):
        return None


def test_decayed_token_refuses_unfenced_write():
    """Review fix: leadership can expire between the caller's ownership
    check and the CAS — writing then would be UNFENCED (the one hole in
    the split-brain argument). The store must refuse, not write."""
    kube = FakeKubeClient()
    store = IntentStore(kube, ShardRing(1), NS,
                        election=_DecayedElection())
    with pytest.raises(StoreFencedError):
        store._cas(0, {"tpumounter.io/l-x": "{}"})
    # nothing reached the cluster
    with pytest.raises(Exception):
        kube.get_config_map(NS, f"{consts.STORE_CONFIGMAP_PREFIX}0")


def test_put_leases_batches_one_cas_per_shard():
    """Review fix: the re-derivation sync lands ALL of a shard's lease
    records in one merge-patch, not one round-trip per lease."""
    kube, store = make_store()
    records = [lease_record(pod=f"p{i}") for i in range(5)]
    before = kube.cm_calls
    store.put_leases(records)
    # one create (map absent) — NOT 5 observe+patch cycles
    assert kube.cm_calls - before <= 2
    leases, _, _ = store.rehydrate(0)
    assert {r.pod for r in leases} == {f"p{i}" for i in range(5)}
    # and a second sync patches once against the cached observation
    before = kube.cm_calls
    store.put_leases(records)
    assert kube.cm_calls - before == 1


def test_forget_shard_zeroes_its_record_gauges():
    """Review fix: a deposed replica must stop exporting the lost
    shard's record counts — frozen gauges double-count against the new
    leader's in any cross-replica sum."""
    kube, store = make_store()
    store.put_lease(lease_record(pod="a"))
    store.put_lease(lease_record(pod="b"))
    assert REGISTRY.store_records.value(kind="lease", shard="0") == 2
    store.forget_shard(0)
    assert REGISTRY.store_records.value(kind="lease", shard="0") == 0
    assert REGISTRY.store_records.value(kind="waiter", shard="0") == 0


def test_decayed_token_parks_instead_of_dropping():
    """Review fix: a mutation issued while leadership validity has
    transiently decayed (lock still names us) must be PARKED, not
    silently dropped — a resumed leadership replays it; only a real
    hand-off (the lock naming a peer) discards it."""
    kube = FakeKubeClient()

    class _Flappy:
        """Election that decayed but whose lock still names us."""

        enabled = True
        replica = "m0"

        def __init__(self):
            self.live = False

        def token(self, shard):
            return 3 if self.live else None

        def leaders(self):
            return {0: {"holder": "m0", "url": "", "fence": 3,
                        "expired": True}}

    election = _Flappy()
    store = IntentStore(kube, ShardRing(1), NS, election=election)
    assert store.put_lease(lease_record(pod="held")) is False
    assert store.snapshot()["dirty"] == 1
    # flush during decay: mutation stays parked (lock still names us)
    assert store.flush_dirty() == 0
    assert store.snapshot()["dirty"] == 1
    # leadership resumes: the parked mutation replays
    election.live = True
    assert store.flush_dirty() == 1
    leases, _, _ = store.rehydrate(0)
    assert {r.pod for r in leases} == {"held"}
    # a REAL hand-off instead: parked mutations are dropped
    election.live = False
    assert store.put_lease(lease_record(pod="late")) is False
    election.leaders = lambda: {0: {"holder": "peer", "url": "",
                                    "fence": 4, "expired": False}}
    assert store.flush_dirty() == 0
    assert store.snapshot()["dirty"] == 0


def test_renew_heartbeats_batch_through_flush_not_per_call():
    """Review fix: renewals are the highest-frequency lease mutation —
    they must NOT issue one synchronous CAS each (a shard's leases all
    share one ConfigMap write stream); the broker tick flushes them as
    one batch per shard."""
    from gpumounter_tpu.master.lease import LeaseTable
    kube, store = make_store()
    table = LeaseTable()
    table.store = store
    for i in range(3):
        table.record("default", f"p{i}", "teamA", "normal",
                     [str(i)], node="node-a", ttl_s=60.0)
    before = kube.cm_calls
    for i in range(3):
        table.renew("default", f"p{i}", 60.0)
    assert kube.cm_calls == before, \
        "renew wrote through synchronously"
    flushed = table.flush_renewals()
    assert flushed == 3
    # ONE patch for the whole batch (plus no extra observes — cached)
    assert kube.cm_calls - before == 1
    leases, _, _ = store.rehydrate(0)
    assert all(r.renewals == 1 for r in leases)
