"""Expert-parallel MoE and pipeline-parallel correctness on the 8-device
CPU mesh (conftest pins JAX_PLATFORMS=cpu with 8 virtual devices):

- MoE: with every expert given IDENTICAL weights and ample capacity, the
  mixture must equal the plain dense FFN (routing becomes irrelevant) —
  an exact oracle for the dispatch/combine plumbing. Expert-sharded vs
  single-device results must also agree.
- Pipeline: the GPipe schedule over n stages must equal running the same
  layers sequentially, and its AD gradients must match the sequential
  model's.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gpumounter_tpu.jaxcheck import moe as moe_lib
from gpumounter_tpu.jaxcheck import pipeline as pipe_lib
from jax.sharding import Mesh


def expert_mesh(expert=4, data=2):
    devs = np.array(jax.devices()[:expert * data]).reshape(data, expert)
    return Mesh(devs, ("data", "expert"))


def pipe_mesh(pipe=4):
    return Mesh(np.array(jax.devices()[:pipe]), ("pipe",))


# -- MoE -----------------------------------------------------------------------


def test_moe_identical_experts_match_dense_ffn():
    cfg = moe_lib.MoEConfig(d_model=16, d_ff=32, n_experts=4,
                            capacity_factor=4.0)     # nothing dropped
    params = moe_lib.init_moe_params(jax.random.PRNGKey(0), cfg)
    # all experts share expert 0's weights
    params["w1"] = jnp.broadcast_to(params["w1"][0], params["w1"].shape)
    params["w2"] = jnp.broadcast_to(params["w2"][0], params["w2"].shape)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))

    out = moe_lib.moe_ffn(params, x, cfg)
    dense = jax.nn.gelu(x @ params["w1"][0]) @ params["w2"][0]
    # combine weights scale by the router prob of the chosen expert
    probs = jax.nn.softmax(
        (x.reshape(-1, 16) @ params["router"]).astype(jnp.float32), -1)
    gate = jnp.max(probs, -1).reshape(2, 8, 1)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(dense * gate), rtol=2e-5)


def test_moe_capacity_drops_to_zero_output():
    """Over-capacity tokens contribute exactly zero (switch semantics)."""
    cfg = moe_lib.MoEConfig(d_model=8, d_ff=16, n_experts=2,
                            capacity_factor=0.01)    # capacity == 1
    params = moe_lib.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 8))
    out = moe_lib.moe_ffn(params, x, cfg)
    # at most n_experts * capacity tokens can be non-zero
    nonzero = np.abs(np.asarray(out)).reshape(6, 8).sum(-1) > 1e-9
    assert nonzero.sum() <= 2


def test_moe_expert_sharded_matches_unsharded():
    cfg = moe_lib.MoEConfig(d_model=16, d_ff=32, n_experts=4)
    params = moe_lib.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16))
    ref = moe_lib.moe_ffn(params, x, cfg)

    mesh = expert_mesh()
    sharded = moe_lib.with_expert_sharding(mesh, params)
    out = jax.jit(lambda p, v: moe_lib.moe_ffn(p, v, cfg))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=1e-6)


def test_moe_train_step_runs_sharded_and_learns():
    cfg = moe_lib.MoEConfig(d_model=16, d_ff=32, n_experts=4)
    mesh = expert_mesh()
    params = moe_lib.with_expert_sharding(
        mesh, moe_lib.init_moe_params(jax.random.PRNGKey(0), cfg))
    step = moe_lib.make_moe_train_step(cfg, mesh)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 16))
    params, first = step(params, x)
    for _ in range(10):
        params, loss = step(params, x)
    assert float(loss) < float(first)


# -- pipeline ------------------------------------------------------------------


def _layers(n, d, key):
    return pipe_lib.make_mlp_layers(n, d, key)


def test_pipeline_matches_sequential():
    d, n_stages, m = 8, 4, 6
    layers = _layers(8, d, jax.random.PRNGKey(0))
    mbs = jax.random.normal(jax.random.PRNGKey(1), (m, 2, d))

    ref = mbs
    for layer in layers:
        ref = pipe_lib.mlp_block(layer, ref)

    mesh = pipe_mesh(n_stages)
    stacked = pipe_lib.place_stage_params(
        mesh, pipe_lib.stack_stage_params(layers, n_stages))
    run = pipe_lib.make_pipeline(mesh, pipe_lib.mlp_block)
    out = jax.jit(run)(stacked, mbs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    d, n_stages = 8, 2
    layers = _layers(4, d, jax.random.PRNGKey(0))
    mbs = jax.random.normal(jax.random.PRNGKey(1), (4, 2, d))
    target = jnp.roll(mbs, 1, axis=-2)

    def seq_loss(layer_list):
        h = mbs
        for layer in layer_list:
            h = pipe_lib.mlp_block(layer, h)
        return jnp.mean(jnp.square(h - target))

    ref_grads = jax.grad(seq_loss)(layers)

    mesh = pipe_mesh(n_stages)
    stacked = pipe_lib.place_stage_params(
        mesh, pipe_lib.stack_stage_params(layers, n_stages))
    pipeline = pipe_lib.make_pipeline(mesh, pipe_lib.mlp_block)

    def pipe_loss(sp):
        return jnp.mean(jnp.square(pipeline(sp, mbs) - target))

    pipe_grads = jax.jit(jax.grad(pipe_loss))(stacked)
    # reshape [n_stages, per, ...] back to per-layer list order
    for i, ref in enumerate(ref_grads):
        stage, idx = divmod(i, len(layers) // n_stages)
        for key in ("w1", "w2"):
            np.testing.assert_allclose(
                np.asarray(pipe_grads[key][stage, idx]),
                np.asarray(ref[key]), rtol=2e-4, atol=1e-6,
                err_msg=f"layer {i} {key}")


def test_pipeline_train_step_learns():
    d, n_stages = 8, 4
    mesh = pipe_mesh(n_stages)
    stacked = pipe_lib.place_stage_params(
        mesh, pipe_lib.stack_stage_params(
            _layers(4, d, jax.random.PRNGKey(0)), n_stages))
    step = pipe_lib.make_pipeline_train_step(mesh)
    mbs = jax.random.normal(jax.random.PRNGKey(1), (4, 2, d))
    stacked, first = step(stacked, mbs)
    for _ in range(10):
        stacked, loss = step(stacked, mbs)
    assert float(loss) < float(first)
