"""Per-request tracing (utils/trace.py: span trees, contextvars
propagation, the TraceStore ring buffer) + the labeled phase histograms:
the attach/detach latency decomposition the reference never had
(SURVEY.md §5: no tracing/profiling of any kind)."""

import json
import urllib.request
import uuid

import pytest

from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.metrics import REGISTRY, LabeledHistogram
from gpumounter_tpu.utils.trace import (NO_STORE, STORE, Trace, TraceStore,
                                        annotate, current_span, span)

from tests.helpers import WorkerRig


def test_trace_collects_and_accumulates_spans():
    trace = Trace("attach", "rid-1")
    with trace.span("allocate"):
        pass
    with trace.span("allocate"):        # repeated phase accumulates
        pass
    with trace.span("actuate"):
        pass
    spans = trace.spans
    assert set(spans) == {"allocate", "actuate"}
    assert all(s >= 0 for s in spans.values())


def test_trace_records_span_despite_exception():
    trace = Trace("attach")
    with pytest.raises(RuntimeError):
        with trace.span("actuate"):
            raise RuntimeError("boom")
    assert "actuate" in trace.spans


def test_trace_finish_feeds_labeled_histogram():
    hist = LabeledHistogram("t_seconds", "test")
    trace = Trace("attach", "rid-2")
    with trace.span("policy"):
        pass
    trace.finish("SUCCESS", hist)
    assert hist.count(phase="policy") == 1
    assert hist.count(phase="allocate") == 0


def test_labeled_histogram_renders_prometheus_exposition():
    hist = LabeledHistogram("x_seconds", "help text", buckets=(0.1, 1.0))
    hist.observe(0.05, phase="allocate")
    hist.observe(5.0, phase="actuate")
    text = "\n".join(hist.render())
    assert "# TYPE x_seconds histogram" in text
    assert 'x_seconds_bucket{phase="allocate",le="0.1"} 1' in text
    assert 'x_seconds_bucket{phase="actuate",le="1"} 0' in text
    assert 'x_seconds_bucket{phase="actuate",le="+Inf"} 1' in text
    assert 'x_seconds_count{phase="allocate"} 1' in text
    # exactly one header pair for the whole family
    assert text.count("# HELP") == 1 and text.count("# TYPE") == 1


def test_labeled_histogram_percentile_per_series():
    hist = LabeledHistogram("y_seconds", "test")
    for v in (0.1, 0.2, 0.3):
        hist.observe(v, phase="a")
    hist.observe(9.0, phase="b")
    assert hist.percentile(50, phase="a") == pytest.approx(0.2)
    assert hist.percentile(50, phase="b") == pytest.approx(9.0)


@pytest.fixture
def rig(fake_host):
    return WorkerRig(fake_host)


def _counts(hist):
    return {d["phase"]: hist.count(**d) for d in hist.phases()}


def test_attach_records_phase_histograms(rig):
    before = _counts(REGISTRY.attach_phase)
    out = rig.service.add_tpu("workload", "default", 2, False)
    assert out.result is consts.AddResult.SUCCESS
    after = _counts(REGISTRY.attach_phase)
    for phase in ("policy", "allocate", "resolve", "actuate"):
        assert after.get(phase, 0) == before.get(phase, 0) + 1, phase
    # no failure -> no rollback span
    assert after.get("rollback", 0) == before.get("rollback", 0)


def test_detach_records_phase_histograms(rig):
    out = rig.service.add_tpu("workload", "default", 2, False)
    before = _counts(REGISTRY.detach_phase)
    res = rig.service.remove_tpu("workload", "default",
                                 [c.uuid for c in out.chips], force=False)
    assert res.result is consts.RemoveResult.SUCCESS
    after = _counts(REGISTRY.detach_phase)
    for phase in ("resolve", "actuate", "cleanup"):
        assert after.get(phase, 0) == before.get(phase, 0) + 1, phase


def test_failed_attach_still_records_ran_phases(rig):
    before = _counts(REGISTRY.attach_phase)
    out = rig.service.add_tpu("ghost", "default", 1, False)
    assert out.result is consts.AddResult.POD_NOT_FOUND
    after = _counts(REGISTRY.attach_phase)
    assert after.get("policy", 0) == before.get("policy", 0) + 1
    # never reached allocation
    assert after.get("allocate", 0) == before.get("allocate", 0)


def test_phase_histograms_render_on_metrics_endpoint(rig):
    rig.service.add_tpu("workload", "default", 1, False)
    text = REGISTRY.render_text()
    assert "tpumounter_attach_phase_seconds_bucket" in text
    assert 'phase="allocate"' in text


def test_policy_denial_counts_as_policy_denied_not_exception(rig):
    from gpumounter_tpu.utils.errors import MountPolicyError
    rig.service.add_tpu("workload", "default", 4, True)
    before = REGISTRY.attach_results.value(result="POLICY_DENIED")
    before_exc = REGISTRY.attach_results.value(result="EXCEPTION")
    with pytest.raises(MountPolicyError):
        rig.service.add_tpu("workload", "default", 1, False)
    assert REGISTRY.attach_results.value(
        result="POLICY_DENIED") == before + 1
    assert REGISTRY.attach_results.value(result="EXCEPTION") == before_exc


def test_labeled_histogram_labelless_series_renders_plain():
    hist = LabeledHistogram("z_seconds", "test", buckets=(1.0,))
    hist.observe(0.5)                    # no labels
    text = "\n".join(hist.render())
    assert 'z_seconds_bucket{le="1"} 1' in text
    assert "{," not in text              # no malformed leading comma


def test_span_tree_nests_under_active_phase():
    """Module-level span() joins the active trace's current phase via the
    contextvar — the deep-layer propagation the tentpole is built on."""
    trace = Trace("attach", "rid-tree")
    with trace.span("allocate"):
        with span("k8s.post", resource="pods"):
            with span("inner"):
                pass
        with span("k8s.list", resource="pods"):
            pass
    trace.finish("SUCCESS", store=NO_STORE)
    allocate = trace.root.children[0]
    assert [c.name for c in allocate.children] == ["k8s.post", "k8s.list"]
    assert allocate.children[0].children[0].name == "inner"
    assert allocate.children[0].attrs == {"resource": "pods"}
    # the flat phase view stays flat: nested spans never become phases
    assert set(trace.spans) == {"allocate"}


def test_span_without_active_trace_is_noop():
    assert current_span() is None
    with span("orphan") as got:
        assert got is None          # body still ran
    annotate(ignored=True)          # no-op, must not raise


def test_trace_span_does_not_nest_into_foreign_trace():
    """A trace opened while another trace's span is current (the master's
    request trace around a slice transaction) keeps its own tree."""
    outer = Trace("request", "rid-outer")
    with outer.activate():
        inner = Trace("slice_attach", "rid-inner")
        with inner.span("validate"):
            pass
    assert [c.name for c in inner.root.children] == ["validate"]
    assert outer.root.children == []


def test_trace_finish_lands_in_store_with_result_and_attrs():
    store = TraceStore()
    trace = Trace("attach", "rid-s1")
    trace.root.attrs["chips"] = 4
    with trace.span("actuate"):
        pass
    trace.finish("SUCCESS", store=store)
    (entry,) = store.find("rid-s1")
    assert entry["op"] == "attach" and entry["result"] == "SUCCESS"
    assert entry["spans"]["attrs"] == {"chips": 4}
    assert entry["spans"]["children"][0]["name"] == "actuate"
    assert entry["total_ms"] >= entry["spans"]["children"][0]["duration_ms"]


def test_trace_store_ring_is_bounded_and_keeps_slowest():
    store = TraceStore(recent_max=5, slowest_max=2)
    slow = Trace("attach", "rid-slow")
    slow._t0 -= 10.0                # fake a 10s-old start: slowest entry
    slow.finish("SUCCESS", store=store)
    for i in range(20):
        Trace("attach", f"rid-{i}").finish("SUCCESS", store=store)
    assert len(store.recent(limit=100)) == 5
    assert store.find("rid-slow") == []          # rotated out of recent
    slowest = store.slowest()
    assert len(slowest) == 2
    assert slowest[0]["rid"] == "rid-slow"       # survived in the top-N


def test_trace_store_filters():
    store = TraceStore()
    t1 = Trace("attach", "rid-a")
    t1.finish("SUCCESS", store=store)
    t2 = Trace("detach", "rid-a")
    t2.finish("EXCEPTION", store=store)
    assert [t["op"] for t in store.recent(rid="rid-a")] == \
        ["detach", "attach"]                     # newest first
    assert [t["op"] for t in store.recent(rid="rid-a",
                                          result="EXCEPTION")] == ["detach"]
    snap = store.snapshot(rid="rid-a", result="SUCCESS")
    assert [t["op"] for t in snap["recent"]] == ["attach"]
    assert all(t["result"] == "SUCCESS" for t in snap["slowest"])


def test_attach_trace_carries_k8s_child_spans(rig):
    """The blind spots, lit: apiserver and kubelet round-trips appear as
    k8s.* child spans inside the phases, and feed the
    tpumounter_k8s_request_seconds{verb,resource} family."""
    lists_before = REGISTRY.k8s_latency.count(verb="LIST",
                                              resource="podresources")
    rid = "trace-k8s-" + uuid.uuid4().hex[:8]
    out = rig.service.add_tpu("workload", "default", 2, False,
                              request_id=rid)
    assert out.result is consts.AddResult.SUCCESS
    (entry,) = STORE.find(rid)

    def names(span_dict):
        yield span_dict["name"]
        for child in span_dict.get("children", []):
            yield from names(child)

    seen = list(names(entry["spans"]))
    assert "k8s.get" in seen          # policy's get_pod
    assert "k8s.list" in seen         # kubelet snapshot / slave LISTs
    assert "scheduler.wait" in seen and "kubelet.resolve" in seen
    # metrics moved with the spans
    assert REGISTRY.k8s_latency.count(
        verb="LIST", resource="podresources") > lists_before
    assert REGISTRY.k8s_latency.count(verb="GET", resource="pods") > 0
    text = REGISTRY.render_text()
    assert ('tpumounter_k8s_request_seconds_bucket{resource="podresources"'
            ',verb="LIST",le="0.005"}') in text
    assert "tpumounter_k8s_request_errors_total" in text


def test_warm_pool_claim_joins_attach_trace(fake_host):
    rig = WorkerRig(fake_host, warm_pool={"entire:2": 1})
    try:
        rig.fill_warm_pool()
        rid = "trace-pool-" + uuid.uuid4().hex[:8]
        out = rig.service.add_tpu("workload", "default", 2, True,
                                  request_id=rid)
        assert out.result is consts.AddResult.SUCCESS
        assert out.pool_hits == 1
        (entry,) = STORE.find(rid)
        allocate = next(c for c in entry["spans"]["children"]
                        if c["name"] == "allocate")
        claim = next(c for c in allocate["children"]
                     if c["name"] == "pool.claim")
        assert claim["attrs"]["key"] == "entire:2"
        assert claim["attrs"]["adopted"] == 1
        assert entry["spans"]["attrs"]["pool_hits"] == 1
    finally:
        rig.close()


def test_failed_attach_trace_reaches_worker_tracez(rig):
    """Satellite: an attach whose actuation raises still records every
    phase it ran plus rollback, lands in the store as EXCEPTION, and is
    served by the worker health port's /tracez — the breakdown matters
    most exactly then."""
    from gpumounter_tpu.utils.errors import ActuationError
    from gpumounter_tpu.worker.main import start_health_server
    rig.actuator.fail_on_create = True
    rid = "trace-fail-" + uuid.uuid4().hex[:8]
    with pytest.raises(ActuationError):
        rig.service.add_tpu("workload", "default", 2, False,
                            request_id=rid)
    (entry,) = STORE.find(rid)
    assert entry["result"] == "EXCEPTION"
    phases = [c["name"] for c in entry["spans"]["children"]]
    for phase in ("policy", "allocate", "resolve", "actuate", "rollback"):
        assert phase in phases, phase
    server = start_health_server(0)
    try:
        url = (f"http://127.0.0.1:{server.server_port}/tracez"
               f"?rid={rid}&result=EXCEPTION")
        with urllib.request.urlopen(url) as resp:
            payload = json.loads(resp.read())
    finally:
        server.shutdown()
    assert [t["rid"] for t in payload["recent"]] == [rid]
    assert payload["recent"][0]["result"] == "EXCEPTION"
    assert "rollback" in [c["name"]
                          for c in payload["recent"][0]["spans"]["children"]]


def test_failed_mount_records_rollback_span(rig):
    """The span that matters most: an actuation failure's trace carries
    rollback timing, and the rollback phase histogram (which the
    TPUMounterRollbacks alert watches) moves."""
    from gpumounter_tpu.utils.errors import ActuationError
    before = _counts(REGISTRY.attach_phase)
    rig.actuator.fail_on_create = True
    with pytest.raises(ActuationError):
        rig.service.add_tpu("workload", "default", 2, False)
    after = _counts(REGISTRY.attach_phase)
    assert after.get("rollback", 0) == before.get("rollback", 0) + 1
    # the phases that ran before the failure are recorded too
    for phase in ("policy", "allocate", "actuate"):
        assert after.get(phase, 0) == before.get(phase, 0) + 1, phase
