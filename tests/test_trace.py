"""Per-request phase tracing (utils/trace.py + the labeled phase
histograms): the attach/detach latency decomposition the reference never
had (SURVEY.md §5: no tracing/profiling of any kind)."""

import pytest

from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.metrics import REGISTRY, LabeledHistogram
from gpumounter_tpu.utils.trace import Trace

from tests.helpers import WorkerRig


def test_trace_collects_and_accumulates_spans():
    trace = Trace("attach", "rid-1")
    with trace.span("allocate"):
        pass
    with trace.span("allocate"):        # repeated phase accumulates
        pass
    with trace.span("actuate"):
        pass
    spans = trace.spans
    assert set(spans) == {"allocate", "actuate"}
    assert all(s >= 0 for s in spans.values())


def test_trace_records_span_despite_exception():
    trace = Trace("attach")
    with pytest.raises(RuntimeError):
        with trace.span("actuate"):
            raise RuntimeError("boom")
    assert "actuate" in trace.spans


def test_trace_finish_feeds_labeled_histogram():
    hist = LabeledHistogram("t_seconds", "test")
    trace = Trace("attach", "rid-2")
    with trace.span("policy"):
        pass
    trace.finish("SUCCESS", hist)
    assert hist.count(phase="policy") == 1
    assert hist.count(phase="allocate") == 0


def test_labeled_histogram_renders_prometheus_exposition():
    hist = LabeledHistogram("x_seconds", "help text", buckets=(0.1, 1.0))
    hist.observe(0.05, phase="allocate")
    hist.observe(5.0, phase="actuate")
    text = "\n".join(hist.render())
    assert "# TYPE x_seconds histogram" in text
    assert 'x_seconds_bucket{phase="allocate",le="0.1"} 1' in text
    assert 'x_seconds_bucket{phase="actuate",le="1"} 0' in text
    assert 'x_seconds_bucket{phase="actuate",le="+Inf"} 1' in text
    assert 'x_seconds_count{phase="allocate"} 1' in text
    # exactly one header pair for the whole family
    assert text.count("# HELP") == 1 and text.count("# TYPE") == 1


def test_labeled_histogram_percentile_per_series():
    hist = LabeledHistogram("y_seconds", "test")
    for v in (0.1, 0.2, 0.3):
        hist.observe(v, phase="a")
    hist.observe(9.0, phase="b")
    assert hist.percentile(50, phase="a") == pytest.approx(0.2)
    assert hist.percentile(50, phase="b") == pytest.approx(9.0)


@pytest.fixture
def rig(fake_host):
    return WorkerRig(fake_host)


def _counts(hist):
    return {d["phase"]: hist.count(**d) for d in hist.phases()}


def test_attach_records_phase_histograms(rig):
    before = _counts(REGISTRY.attach_phase)
    out = rig.service.add_tpu("workload", "default", 2, False)
    assert out.result is consts.AddResult.SUCCESS
    after = _counts(REGISTRY.attach_phase)
    for phase in ("policy", "allocate", "resolve", "actuate"):
        assert after.get(phase, 0) == before.get(phase, 0) + 1, phase
    # no failure -> no rollback span
    assert after.get("rollback", 0) == before.get("rollback", 0)


def test_detach_records_phase_histograms(rig):
    out = rig.service.add_tpu("workload", "default", 2, False)
    before = _counts(REGISTRY.detach_phase)
    res = rig.service.remove_tpu("workload", "default",
                                 [c.uuid for c in out.chips], force=False)
    assert res.result is consts.RemoveResult.SUCCESS
    after = _counts(REGISTRY.detach_phase)
    for phase in ("resolve", "actuate", "cleanup"):
        assert after.get(phase, 0) == before.get(phase, 0) + 1, phase


def test_failed_attach_still_records_ran_phases(rig):
    before = _counts(REGISTRY.attach_phase)
    out = rig.service.add_tpu("ghost", "default", 1, False)
    assert out.result is consts.AddResult.POD_NOT_FOUND
    after = _counts(REGISTRY.attach_phase)
    assert after.get("policy", 0) == before.get("policy", 0) + 1
    # never reached allocation
    assert after.get("allocate", 0) == before.get("allocate", 0)


def test_phase_histograms_render_on_metrics_endpoint(rig):
    rig.service.add_tpu("workload", "default", 1, False)
    text = REGISTRY.render_text()
    assert "tpumounter_attach_phase_seconds_bucket" in text
    assert 'phase="allocate"' in text


def test_policy_denial_counts_as_policy_denied_not_exception(rig):
    from gpumounter_tpu.utils.errors import MountPolicyError
    rig.service.add_tpu("workload", "default", 4, True)
    before = REGISTRY.attach_results.value(result="POLICY_DENIED")
    before_exc = REGISTRY.attach_results.value(result="EXCEPTION")
    with pytest.raises(MountPolicyError):
        rig.service.add_tpu("workload", "default", 1, False)
    assert REGISTRY.attach_results.value(
        result="POLICY_DENIED") == before + 1
    assert REGISTRY.attach_results.value(result="EXCEPTION") == before_exc


def test_labeled_histogram_labelless_series_renders_plain():
    hist = LabeledHistogram("z_seconds", "test", buckets=(1.0,))
    hist.observe(0.5)                    # no labels
    text = "\n".join(hist.render())
    assert 'z_seconds_bucket{le="1"} 1' in text
    assert "{," not in text              # no malformed leading comma


def test_failed_mount_records_rollback_span(rig):
    """The span that matters most: an actuation failure's trace carries
    rollback timing, and the rollback phase histogram (which the
    TPUMounterRollbacks alert watches) moves."""
    from gpumounter_tpu.utils.errors import ActuationError
    before = _counts(REGISTRY.attach_phase)
    rig.actuator.fail_on_create = True
    with pytest.raises(ActuationError):
        rig.service.add_tpu("workload", "default", 2, False)
    after = _counts(REGISTRY.attach_phase)
    assert after.get("rollback", 0) == before.get("rollback", 0) + 1
    # the phases that ran before the failure are recorded too
    for phase in ("policy", "allocate", "actuate"):
        assert after.get(phase, 0) == before.get(phase, 0) + 1, phase
