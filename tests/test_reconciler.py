"""Orphan reconciliation + worker-restart recovery: state is re-derived from
the cluster (kubelet listing + slave labels), never from worker memory —
SURVEY.md §5's recoverability property, made explicit and tested."""

import time

from gpumounter_tpu.utils import consts
from gpumounter_tpu.worker.reconciler import OrphanReconciler
from gpumounter_tpu.worker.service import TPUMountService

from tests.helpers import WorkerRig


def test_orphan_deleted_when_owner_gone(fake_host):
    from gpumounter_tpu.utils.metrics import REGISTRY
    rig = WorkerRig(fake_host)
    rig.service.add_tpu("workload", "default", 2, False)
    assert len(rig.sim.slave_pods()) == 2

    before = REGISTRY.orphans_reclaimed.value()
    rig.sim.kube.delete_pod("default", "workload")
    reconciler = OrphanReconciler(rig.sim.kube, rig.sim.settings)
    deleted = reconciler.scan_once()
    assert len(deleted) == 2
    assert rig.sim.slave_pods() == []
    assert rig.sim.podresources.assignments == {}    # chips released
    # GC is observable: the reclaim counter moved with the deletions
    assert REGISTRY.orphans_reclaimed.value() == before + 2


def test_orphan_deleted_when_owner_terminal(fake_host):
    rig = WorkerRig(fake_host)
    rig.service.add_tpu("workload", "default", 1, True)
    rig.sim.kube.set_pod_status("default", "workload", phase="Succeeded")
    deleted = OrphanReconciler(rig.sim.kube, rig.sim.settings).scan_once()
    assert len(deleted) == 1
    assert rig.sim.slave_pods() == []


def test_live_owner_keeps_slaves(fake_host):
    rig = WorkerRig(fake_host)
    rig.service.add_tpu("workload", "default", 2, False)
    deleted = OrphanReconciler(rig.sim.kube, rig.sim.settings).scan_once()
    assert deleted == []
    assert len(rig.sim.slave_pods()) == 2


def test_other_nodes_slaves_untouched(fake_host):
    rig = WorkerRig(fake_host)
    rig.service.add_tpu("workload", "default", 1, False)
    rig.sim.kube.delete_pod("default", "workload")
    # this worker believes it runs on another node
    rig.sim.settings.node_name = "node-elsewhere"
    reconciler = OrphanReconciler(rig.sim.kube, rig.sim.settings)
    assert reconciler.scan_once() == []
    assert len(rig.sim.slave_pods()) == 1
    # the node's own worker would clean it
    rig.sim.settings.node_name = "node-a"
    assert len(reconciler.scan_once()) == 1


def test_unlabelled_pool_pods_left_alone(fake_host):
    rig = WorkerRig(fake_host)
    rig.sim.kube.put_pod({
        "metadata": {"name": "hand-made", "namespace":
                     rig.sim.settings.pool_namespace,
                     "labels": {consts.SLAVE_POD_LABEL_KEY:
                                consts.SLAVE_POD_LABEL_VALUE}},
        "spec": {}, "status": {"phase": "Running"},
    })
    assert OrphanReconciler(rig.sim.kube, rig.sim.settings).scan_once() == []


def test_background_loop_runs(fake_host):
    rig = WorkerRig(fake_host)
    rig.service.add_tpu("workload", "default", 1, False)
    rig.sim.kube.delete_pod("default", "workload")
    reconciler = OrphanReconciler(rig.sim.kube, rig.sim.settings,
                                  interval_s=0.05).start()
    try:
        deadline = time.time() + 3
        while time.time() < deadline and rig.sim.slave_pods():
            time.sleep(0.02)
        assert rig.sim.slave_pods() == []
    finally:
        reconciler.stop()


def test_recreated_owner_does_not_adopt_stale_slaves(fake_host):
    """StatefulSet pattern: owner dies and is recreated under the same name
    with a new UID — the old slave pods are still orphans."""
    rig = WorkerRig(fake_host)
    rig.service.add_tpu("workload", "default", 1, False)
    rig.sim.kube.delete_pod("default", "workload")
    # recreated immediately with a fresh UID
    from gpumounter_tpu.testing.sim import make_target_pod
    reborn = make_target_pod(uid="uid-reborn")
    rig.sim.kube.put_pod(reborn)
    rig.provision_container(reborn)
    deleted = OrphanReconciler(rig.sim.kube, rig.sim.settings).scan_once()
    assert len(deleted) == 1
    assert rig.sim.slave_pods() == []
    # and the reborn pod can mount fresh
    out = rig.service.add_tpu("workload", "default", 1, True)
    assert out.result is consts.AddResult.SUCCESS


def test_same_pod_name_other_namespace_not_conflated(fake_host):
    """default/workload and team-b/workload share the node; team-b's mount
    must be invisible to default's mount-type/status/removal resolution."""
    rig = WorkerRig(fake_host)
    team_b = rig.sim.add_target_pod(namespace="team-b", uid="uid-team-b")
    rig.provision_container(team_b)
    assert rig.service.add_tpu("workload", "team-b", 2, True).result is \
        consts.AddResult.SUCCESS

    # default/workload sees no mount and can entire-mount the rest
    assert rig.service.tpu_status("workload", "default")[0] is \
        consts.MountType.NONE
    out = rig.service.remove_tpu("workload", "default", [], False)
    assert out.result is consts.RemoveResult.TPU_NOT_FOUND
    assert rig.service.add_tpu("workload", "default", 2, True).result is \
        consts.AddResult.SUCCESS
    # each namespace's status shows exactly its own chips
    _, chips_default = rig.service.tpu_status("workload", "default")
    _, chips_teamb = rig.service.tpu_status("workload", "team-b")
    assert len(chips_default) == 2 and len(chips_teamb) == 2
    assert {c.device_id for c in chips_default}.isdisjoint(
        {c.device_id for c in chips_teamb})


def test_txn_scoped_removal(fake_host):
    """remove_tpu(txn_id=...) touches only that transaction's chips."""
    rig = WorkerRig(fake_host)
    rig.service.add_tpu("workload", "default", 1, False)            # no txn
    rig.service.add_tpu("workload", "default", 1, False,
                        txn_id="txn-abc")
    out = rig.service.remove_tpu("workload", "default", [], False,
                                 txn_id="txn-abc")
    assert out.result is consts.RemoveResult.SUCCESS
    # the non-txn mount survives
    mount_type, chips = rig.service.tpu_status("workload", "default")
    assert mount_type is consts.MountType.SINGLE
    assert len(chips) == 1
    # unknown txn is an idempotent no-op
    out = rig.service.remove_tpu("workload", "default", [], False,
                                 txn_id="txn-ghost")
    assert out.result is consts.RemoveResult.TPU_NOT_FOUND


def test_worker_restart_can_detach_previous_workers_mounts(fake_host):
    """A NEW worker stack (fresh service objects, same cluster/host state)
    must be able to detach chips a previous worker attached — nothing about
    a mount may live only in worker memory."""
    rig = WorkerRig(fake_host)
    added = rig.service.add_tpu("workload", "default", 2, False)
    assert added.result is consts.AddResult.SUCCESS

    # "restart": rebuild allocator/mounter/service from scratch over the
    # same simulated cluster and host tree
    from gpumounter_tpu.allocator import TPUAllocator
    from gpumounter_tpu.actuation.mount import TPUMounter
    fresh_allocator = TPUAllocator(rig.sim.collector, rig.sim.kube,
                                   rig.sim.settings)
    fresh_mounter = TPUMounter(rig.cgroups, rig.actuator,
                               rig.sim.enumerator, rig.host)
    fresh_service = TPUMountService(fresh_allocator, fresh_mounter,
                                    rig.sim.kube, rig.sim.settings)

    assert fresh_service.tpu_status("workload", "default")[0] is \
        consts.MountType.SINGLE
    out = fresh_service.remove_tpu("workload", "default",
                                   [c.uuid for c in added.chips], False)
    assert out.result is consts.RemoveResult.SUCCESS
    assert rig.sim.slave_pods() == []
