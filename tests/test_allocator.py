"""Allocator tests over the ClusterSim scripted scheduler.

Covers the reference flows (allocator.go): allocation fan-out, unschedulable
cleanup, removal resolution, slave pod deletion, mount-type resolution — plus
the deliberate fixes (timeouts, subset removal, labelled mount type).
"""

import pytest

from gpumounter_tpu.allocator import TPUAllocator
from gpumounter_tpu.k8s import objects
from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import Settings
from gpumounter_tpu.utils.errors import (AllocationTimeoutError,
                                         DeviceNotFoundError,
                                         InsufficientTPUError)

from tests.helpers import ClusterSim


@pytest.fixture
def sim():
    return ClusterSim(n_chips=4)


@pytest.fixture
def allocator(sim):
    return TPUAllocator(sim.collector, sim.kube, sim.settings)


def test_single_mount_allocates_n_slave_pods(sim, allocator):
    owner = sim.add_target_pod()
    chips, slaves = allocator.get_available_tpus(owner, 2, 1)
    assert len(chips) == 2
    assert len(slaves) == 2
    assert len(sim.slave_pods()) == 2
    for pod in sim.slave_pods():
        labels = objects.labels(pod)
        assert labels[consts.OWNER_POD_LABEL_KEY] == "workload"
        assert labels[consts.MOUNT_TYPE_LABEL_KEY] == \
            consts.MountType.SINGLE.value
        assert pod["spec"]["nodeSelector"]["kubernetes.io/hostname"] == \
            "node-a"


def test_entire_mount_is_one_slave_pod(sim, allocator):
    owner = sim.add_target_pod()
    chips, slaves = allocator.get_available_tpus(owner, 4, 4)
    assert len(chips) == 4
    assert len(slaves) == 1
    pod = sim.slave_pods()[0]
    limits = pod["spec"]["containers"][0]["resources"]["limits"]
    assert limits[consts.TPU_RESOURCE_NAME] == "4"
    assert objects.labels(pod)[consts.MOUNT_TYPE_LABEL_KEY] == \
        consts.MountType.ENTIRE.value


def test_insufficient_chips_cleans_up(sim, allocator):
    owner = sim.add_target_pod()
    with pytest.raises(InsufficientTPUError):
        allocator.get_available_tpus(owner, 5, 1)
    # every created slave pod must be deleted again
    assert sim.slave_pods() == []
    assert sim.podresources.assignments == {}


def test_allocation_times_out_when_scheduler_never_acts(sim):
    sim.kube.on_create.clear()        # scheduler goes dark
    settings = Settings()
    settings.allocation_timeout_s = 0.3
    allocator = TPUAllocator(sim.collector, sim.kube, settings)
    owner = sim.add_target_pod()
    with pytest.raises(AllocationTimeoutError):
        allocator.get_available_tpus(owner, 1, 1)
    assert sim.slave_pods() == []


def test_slow_scheduler_still_succeeds(sim):
    sim.schedule_delay_s = 0.15
    allocator = TPUAllocator(sim.collector, sim.kube, sim.settings)
    owner = sim.add_target_pod()
    chips, _ = allocator.get_available_tpus(owner, 2, 2)
    assert len(chips) == 2


def test_removable_resolution_subset_and_unknown(sim, allocator):
    owner = sim.add_target_pod()
    chips, slaves = allocator.get_available_tpus(owner, 2, 1)
    uuids = [c.uuid for c in chips]

    got, holders, _ = allocator.get_removable_tpus("workload", [uuids[0]])
    assert [c.uuid for c in got] == [uuids[0]]
    assert len(holders) == 1

    got, holders, _ = allocator.get_removable_tpus("workload", [])
    assert sorted(c.uuid for c in got) == sorted(uuids)
    assert holders == sorted(slaves)

    with pytest.raises(DeviceNotFoundError):
        allocator.get_removable_tpus("workload", ["no-such-chip"])


def test_chips_from_pod_own_spec_are_not_removable(sim, allocator):
    # The target pod got chip "0" through its own spec (kubelet-assigned).
    sim.podresources.assign("default", "workload", ["0"])
    sim.add_target_pod()
    with pytest.raises(DeviceNotFoundError):
        allocator.get_removable_tpus("workload", ["0"])


def test_delete_slave_pods_waits_for_termination(sim, allocator):
    owner = sim.add_target_pod()
    _, slaves = allocator.get_available_tpus(owner, 2, 1)
    sim.kube.delete_latency_s = 0.1       # graceful termination
    allocator.delete_slave_pods(slaves)
    assert sim.slave_pods() == []


def test_mount_type_from_labels(sim, allocator):
    owner = sim.add_target_pod()
    assert allocator.get_mount_type("workload") is consts.MountType.NONE
    allocator.get_available_tpus(owner, 2, 2)
    assert allocator.get_mount_type("workload") is consts.MountType.ENTIRE


def test_mount_type_single(sim, allocator):
    owner = sim.add_target_pod()
    allocator.get_available_tpus(owner, 1, 1)
    assert allocator.get_mount_type("workload") is consts.MountType.SINGLE


def test_slave_pod_spec_conventions(sim, allocator):
    owner = sim.add_target_pod()
    spec = allocator.new_slave_pod(owner, 1, entire=False)
    assert spec["metadata"]["name"].startswith(
        "workload" + consts.SLAVE_POD_INFIX)
    assert spec["metadata"]["namespace"] == sim.settings.pool_namespace
    container = spec["spec"]["containers"][0]
    assert container["image"] == consts.SLAVE_POD_IMAGE
    assert spec["spec"]["tolerations"][0]["key"] == consts.TPU_RESOURCE_NAME
    # distinct random suffixes
    names = {allocator.new_slave_pod(owner, 1, False)["metadata"]["name"]
             for _ in range(8)}
    assert len(names) == 8


# -- watch-from-resourceVersion (VERDICT weak #8) ------------------------------


class _HookedKube(FakeKubeClient):
    """FakeKubeClient that fires a callback right after LIST returns (the
    lost-event window) and counts get_pod calls (polling detector)."""

    def __init__(self):
        super().__init__()
        self.after_list = None
        self.get_pod_calls = 0
        self.fail_first_watch_with_410 = False

    def list_pods_with_version(self, namespace, label_selector=None):
        out = super().list_pods_with_version(namespace, label_selector)
        hook, self.after_list = self.after_list, None
        if hook:
            hook()
        return out

    def get_pod(self, namespace, name):
        self.get_pod_calls += 1
        return super().get_pod(namespace, name)

    def watch_pods(self, *args, **kwargs):
        if self.fail_first_watch_with_410:
            self.fail_first_watch_with_410 = False
            from gpumounter_tpu.utils.errors import K8sApiError
            raise K8sApiError(410, "resourceVersion too old")
        return super().watch_pods(*args, **kwargs)


def _slave_pod(name, phase="Pending"):
    return {"metadata": {"name": name, "namespace": "tpu-pool",
                         "labels": {consts.SLAVE_POD_LABEL_KEY:
                                    consts.SLAVE_POD_LABEL_VALUE}},
            "status": {"phase": phase}}


def _rv_allocator(kube):
    settings = Settings()
    settings.allocation_timeout_s = 3.0
    return TPUAllocator(collector=None, kube=kube, settings=settings)


def test_wait_running_catches_event_between_list_and_watch():
    """A Running transition landing AFTER the LIST but BEFORE the watch
    starts is replayed because the watch begins at the LIST's
    resourceVersion — no re-sweep polling needed (get_pod never called)."""
    kube = _HookedKube()
    kube.put_pod(_slave_pod("s1"))
    alloc = _rv_allocator(kube)
    kube.after_list = lambda: kube.set_pod_status("tpu-pool", "s1",
                                                  phase="Running")
    alloc._wait_running(["s1"])                     # must not time out
    assert kube.get_pod_calls == 0                  # event-driven, no polls


def test_wait_deleted_catches_event_between_list_and_watch():
    kube = _HookedKube()
    kube.put_pod(_slave_pod("s1", phase="Running"))
    alloc = _rv_allocator(kube)
    kube.after_list = lambda: kube.delete_pod("tpu-pool", "s1")
    alloc._wait_deleted(["s1"])
    assert kube.get_pod_calls == 0


def test_wait_running_recovers_from_410_gone():
    """An expired resourceVersion (410) triggers a re-LIST + fresh watch
    instead of failing the allocation."""
    kube = _HookedKube()
    kube.put_pod(_slave_pod("s1", phase="Running"))
    alloc = _rv_allocator(kube)
    kube.fail_first_watch_with_410 = True
    # pod Pending at first list; 410 on first watch; second list sees Running
    kube._pods[("tpu-pool", "s1")]["status"]["phase"] = "Pending"
    kube.after_list = lambda: kube.set_pod_status("tpu-pool", "s1",
                                                  phase="Running")
    alloc._wait_running(["s1"])


# -- kubelet PodResources lag (VERDICT weak #4) --------------------------------


def test_kubelet_lag_tolerated_with_bounded_retry(fake_host):
    """The PodResources listing trails the Running transition by 0.8s (the
    real device plugin is asynchronous): allocation must retry and
    succeed, not raise InsufficientTPU on the first empty read."""
    from tests.helpers import WorkerRig
    rig = WorkerRig(fake_host, n_chips=4, kubelet_lag_s=0.8)
    try:
        outcome = rig.service.add_tpu("workload", "default", 4, True)
        assert outcome.result == consts.AddResult.SUCCESS
        assert len(outcome.chips) == 4
    finally:
        rig.close()


def test_kubelet_lag_beyond_bound_fails_cleanly(fake_host):
    """Lag past the bound is a failure — with every slave pod this call
    created cleaned up."""
    from tests.helpers import WorkerRig
    rig = WorkerRig(fake_host, n_chips=4, kubelet_lag_s=5.0)
    rig.sim.settings.kubelet_lag_timeout_s = 0.3
    try:
        outcome = rig.service.add_tpu("workload", "default", 4, True)
        assert outcome.result == consts.AddResult.INSUFFICIENT_TPU
        assert "reports no" in outcome.message
        assert rig.sim.slave_pods() == []
    finally:
        rig.close()
