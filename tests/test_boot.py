"""Process-level boot tests: launch the REAL binaries
(``python -m gpumounter_tpu.worker.main`` / ``master.main``) as
subprocesses against a live HTTP apiserver facade + unix-socket kubelet,
and drive the QuickStart flow through them with ``tpumounterctl``.

This is the layer nothing else covers: Settings.from_env wiring, the
default_kube_client kubeconfig path inside the binaries, health/readiness
endpoints, gRPC serving, and clean SIGTERM shutdown — the exact things a
deploy typo breaks. Everything here runs the production object graph; the
only fakes are the cluster (FakeKubeClient behind real HTTP) and the chips
(fixture files, TPU_ALLOW_FAKE_DEVICES=1 — BASELINE config 1 at the
process level). Device nodes are created by REAL mknod through the
fixture /proc/<pid>/root (the test runs as root).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

# the worker does a REAL mknod(S_IFCHR) into the fixture /proc/<pid>/root
pytestmark = pytest.mark.skipif(os.geteuid() != 0,
                                reason="boot tests need root (mknod)")

from gpumounter_tpu.testing.http_apiserver import (HttpApiserver,  # noqa: E402
                                                   write_kubeconfig)
from gpumounter_tpu.testing.sim import ClusterSim, worker_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def pick_ports() -> tuple[int, int]:
    """(grpc_port, master_port) such that grpc_port+1 (the worker health
    port) is also bindable and all three are distinct — avoids the flake
    where the OS hands out master_port == grpc_port+1."""
    for _ in range(50):
        socks = []
        try:
            a = socket.socket()
            a.bind(("127.0.0.1", 0))
            grpc_port = a.getsockname()[1]
            socks.append(a)
            b = socket.socket()
            b.bind(("127.0.0.1", grpc_port + 1))
            socks.append(b)
            c = socket.socket()
            c.bind(("127.0.0.1", 0))
            master_port = c.getsockname()[1]
            socks.append(c)
            return grpc_port, master_port
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port triple found")


def wait_http(url: str, timeout_s: float = 20.0,
              expect: int = 200) -> None:
    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as resp:
                if resp.status == expect:
                    return
                last = resp.status
        except Exception as e:
            last = e
        time.sleep(0.1)
    raise AssertionError(f"{url} not up within {timeout_s}s: {last}")


@pytest.fixture
def boot_fake_host():
    """Like the shared ``fake_host`` but rooted on tmpfs when available:
    the boot tests do REAL ``mknod(S_IFCHR)`` into the fixture tree, and
    network/overlay filesystems (9p /tmp on some dev hosts) refuse char
    nodes even for root — tmpfs behaves like the real devtmpfs."""
    import shutil
    import tempfile
    from gpumounter_tpu.utils.config import HostPaths
    base = "/dev/shm" if os.access("/dev/shm", os.W_OK) else None
    root = tempfile.mkdtemp(prefix="tpumounter-boot-", dir=base)
    host = HostPaths(
        dev_root=os.path.join(root, "dev"),
        proc_root=os.path.join(root, "proc"),
        sys_root=os.path.join(root, "sys"),
        cgroup_root=os.path.join(root, "sys", "fs", "cgroup"),
        kubelet_socket=os.path.join(root, "pod-resources", "kubelet.sock"))
    for d in (host.dev_root, host.proc_root, host.cgroup_root):
        os.makedirs(d, exist_ok=True)
    yield host
    shutil.rmtree(root, ignore_errors=True)


@pytest.fixture
def boot_env(boot_fake_host, tmp_path):
    """ClusterSim + HTTP apiserver + kubeconfig + fixture container, and
    the env both binaries boot from."""
    fake_host = boot_fake_host
    sim = ClusterSim(n_chips=4, kubelet_socket_path=fake_host.kubelet_socket)
    sim.settings.host = fake_host
    # fixture chips on "disk" so the worker subprocess's enumerator sees the
    # same uuids the sim's scheduler assigns (fake-chip file format of
    # device/enumerator.py: regular accelN + majmin sidecar)
    for i in range(4):
        open(os.path.join(fake_host.dev_root, f"accel{i}"), "w").close()
        with open(os.path.join(fake_host.dev_root,
                               f"accel{i}.majmin"), "w") as f:
            f.write(f"120:{i}")
    api = HttpApiserver(sim.kube)
    kubeconfig = write_kubeconfig(str(tmp_path / "kubeconfig"), api.base)

    pod = sim.add_target_pod(name="workload")

    # fixture container: cgroup dir with one live PID + /proc/<pid>/root/dev
    from gpumounter_tpu.actuation.cgroup import CgroupDeviceController
    from gpumounter_tpu.k8s import objects
    cgroups = CgroupDeviceController(fake_host, driver="cgroupfs", version=1)
    cid = objects.container_ids(pod)[0]
    cgroup_dir = cgroups.container_dir(pod, cid)
    os.makedirs(cgroup_dir, exist_ok=True)
    pid = 4242
    with open(os.path.join(cgroup_dir, "cgroup.procs"), "w") as f:
        f.write(f"{pid}\n")
    os.makedirs(os.path.join(fake_host.proc_root, str(pid), "root", "dev"),
                exist_ok=True)

    grpc_port, master_port = pick_ports()
    env = dict(os.environ)
    env.pop("KUBERNETES_SERVICE_HOST", None)
    env.update({
        "KUBECONFIG": kubeconfig,
        "PYTHONPATH": REPO,
        "TPU_ALLOW_FAKE_DEVICES": "1",
        "CGROUP_DRIVER": "cgroupfs",
        "NODE_NAME": sim.node,
        "TPU_DEV_ROOT": fake_host.dev_root,
        "TPU_PROC_ROOT": fake_host.proc_root,
        "TPU_SYS_ROOT": fake_host.sys_root,
        "TPU_CGROUP_ROOT": fake_host.cgroup_root,
        "TPU_KUBELET_SOCKET": fake_host.kubelet_socket,
        "TPU_WORKER_GRPC_PORT": str(grpc_port),
        "TPU_MASTER_HTTP_PORT": str(master_port),
        "TPU_ALLOCATION_TIMEOUT_S": "20",
        "TPU_KUBELET_LAG_TIMEOUT_S": "5",
    })
    procs = []

    def launch(module: str) -> subprocess.Popen:
        p = subprocess.Popen(
            [sys.executable, "-m", module], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        procs.append(p)
        return p

    yield {"sim": sim, "env": env, "launch": launch,
           "grpc_port": grpc_port, "master_port": master_port,
           "fake_host": fake_host, "pid": pid, "cgroup_dir": cgroup_dir}

    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
    api.close()
    sim.close()


def _cli(master_port: int, *argv) -> tuple[int, str]:
    out = subprocess.run(
        [sys.executable, "-m", "gpumounter_tpu.cli",
         "--master", f"http://127.0.0.1:{master_port}", *argv],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "PYTHONPATH": REPO})
    return out.returncode, out.stdout


def test_worker_and_master_binaries_end_to_end(boot_env):
    b = boot_env
    worker = b["launch"]("gpumounter_tpu.worker.main")
    health = f"http://127.0.0.1:{b['grpc_port'] + 1}"
    wait_http(f"{health}/readyz")
    assert worker.poll() is None

    # register the (real) worker in discovery, then boot the master
    b["sim"].kube.put_pod(worker_pod(b["sim"].node, "127.0.0.1",
                                     grpc_port=b["grpc_port"]))
    master = b["launch"]("gpumounter_tpu.master.main")
    wait_http(f"http://127.0.0.1:{b['master_port']}/healthz")
    assert master.poll() is None

    # QuickStart flow through the full production stack via the CLI
    rc, out = _cli(b["master_port"], "add", "workload", "-n", "default",
                   "--tpus", "4", "--entire")
    assert rc == 0, out
    assert "SUCCESS" in out

    # real mknod happened inside the fixture container's /dev
    devdir = os.path.join(b["fake_host"].proc_root, str(b["pid"]),
                          "root", "dev")
    nodes = sorted(n for n in os.listdir(devdir) if n.startswith("accel"))
    assert nodes == ["accel0", "accel1", "accel2", "accel3"]
    import stat
    st = os.stat(os.path.join(devdir, "accel0"))
    assert stat.S_ISCHR(st.st_mode)         # a genuine device node

    # cgroup v1 grant written for every chip
    with open(os.path.join(b["cgroup_dir"], "devices.allow")) as f:
        grants = f.read()
    assert grants.count("c 120:") == 4 and "rw" in grants

    rc, out = _cli(b["master_port"], "status", "workload")
    assert rc == 0 and "mount_type=entire" in out

    rc, out = _cli(b["master_port"], "remove", "workload",
                   "--uuids", "0,1,2,3")
    assert rc == 0, out
    assert not [n for n in os.listdir(devdir) if n.startswith("accel")]
    assert b["sim"].slave_pods() == []

    # metrics surfaced on the worker health port
    with urllib.request.urlopen(f"{health}/metrics") as resp:
        metrics = resp.read().decode()
    assert "attach_seconds" in metrics
    assert "tpumounter_node_chips" in metrics

    # the audit-trail Events crossed the process boundary: worker binary ->
    # kubeconfig client -> HTTP facade -> FakeKubeClient store
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and \
            len(b["sim"].kube.events) < 2:
        time.sleep(0.05)
    reasons = [e["reason"] for e in b["sim"].kube.events]
    assert reasons == ["TPUAttached", "TPUDetached"], reasons

    # clean shutdown on SIGTERM: default handler (no traceback-exit-1)
    worker.send_signal(signal.SIGTERM)
    master.send_signal(signal.SIGTERM)
    assert worker.wait(timeout=10) in (0, -signal.SIGTERM)
    assert master.wait(timeout=10) in (0, -signal.SIGTERM)


def test_worker_watch_stream_over_http(boot_env):
    """With a delayed scheduler, the worker's _wait_running must consume
    the WATCH STREAM through the HTTP facade (the synchronous-schedule test
    resolves everything in the initial LIST, so the streaming path of
    RestKubeClient.watch_pods would otherwise never run cross-process)."""
    b = boot_env
    b["sim"].schedule_delay_s = 0.8
    worker = b["launch"]("gpumounter_tpu.worker.main")
    wait_http(f"http://127.0.0.1:{b['grpc_port'] + 1}/readyz")

    from gpumounter_tpu.worker.grpc_server import WorkerClient
    client = WorkerClient(f"127.0.0.1:{b['grpc_port']}")
    try:
        t0 = time.monotonic()
        resp = client.add_tpu("workload", "default", 4,
                              is_entire_mount=True, request_id="watch-rid")
        elapsed = time.monotonic() - t0
        assert resp.result == 0, resp
        assert len(resp.device_ids) == 4
        # the schedule delay really gated the attach (watch, not busy-poll)
        assert elapsed >= 0.8
    finally:
        client.close()
    worker.send_signal(signal.SIGTERM)
    assert worker.wait(timeout=10) in (0, -signal.SIGTERM)


def test_worker_killed_mid_attach_retry_adopts(boot_env):
    """Worker dies (SIGKILL) after creating slave pods but before the
    mount completes; a FRESH worker process serving the same node resumes
    the retry of the same request id by adopting the surviving slave pod —
    no double-allocation, attach completes. The whole idempotency story at
    the process level."""
    import grpc

    b = boot_env
    b["sim"].schedule_delay_s = 2.0     # widen the kill window
    worker = b["launch"]("gpumounter_tpu.worker.main")
    wait_http(f"http://127.0.0.1:{b['grpc_port'] + 1}/readyz")

    from gpumounter_tpu.worker.grpc_server import WorkerClient
    client = WorkerClient(f"127.0.0.1:{b['grpc_port']}")
    result = {}

    def attach():
        try:
            result["resp"] = client.add_tpu(
                "workload", "default", 4, is_entire_mount=True,
                request_id="kill-rid")
        except grpc.RpcError as e:
            result["error"] = e.code()

    import threading
    t = threading.Thread(target=attach)
    t.start()
    # wait until the in-flight attach has created its slave pod
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not b["sim"].slave_pods():
        time.sleep(0.05)
    assert b["sim"].slave_pods(), "slave pod never appeared"
    worker.send_signal(signal.SIGKILL)
    worker.wait(timeout=10)
    t.join(timeout=30)
    client.close()
    assert result.get("error") is not None      # caller saw UNAVAILABLE

    # surviving slave pod is still there (reply was lost, chips reserved)
    assert len(b["sim"].slave_pods()) == 1

    worker2 = b["launch"]("gpumounter_tpu.worker.main")
    wait_http(f"http://127.0.0.1:{b['grpc_port'] + 1}/readyz")
    client2 = WorkerClient(f"127.0.0.1:{b['grpc_port']}")
    try:
        resp = client2.add_tpu("workload", "default", 4,
                               is_entire_mount=True, request_id="kill-rid")
        assert resp.result == 0, resp
        assert len(resp.device_ids) == 4
    finally:
        client2.close()
    # adoption, not re-allocation: still exactly one slave pod
    assert len(b["sim"].slave_pods()) == 1
    devdir = os.path.join(b["fake_host"].proc_root, str(b["pid"]),
                          "root", "dev")
    assert sorted(n for n in os.listdir(devdir)
                  if n.startswith("accel") and not n.endswith("majmin")) == \
        ["accel0", "accel1", "accel2", "accel3"]
    worker2.send_signal(signal.SIGTERM)
    assert worker2.wait(timeout=10) in (0, -signal.SIGTERM)


def test_worker_fails_fast_without_kubelet(boot_env, tmp_path):
    """Ref SURVEY §3.1: the worker exits rather than serve with a broken
    stack (no kubelet socket ⇒ deploy error)."""
    b = boot_env
    b["env"]["TPU_KUBELET_SOCKET"] = str(tmp_path / "absent.sock")
    worker = b["launch"]("gpumounter_tpu.worker.main")
    assert worker.wait(timeout=30) != 0
