"""Chip utilization & device-access accounting plane (ISSUE 10).

Unit coverage for the worker-side sampler (collector/usage.py): probe
seam, ownership attribution, open/close accounting, /utilz; the fleet
aggregator's scrape join (per-node + per-tenant utilization, idle-lease
list); the broker's idle marking + idle-aware preemption preference; and
the acceptance e2e on the sim stack — two tenants with live leases, one
goes idle, is flagged fleet-wide, doctor WARNs, and a high-priority
waiter preempts the idle lease before the busy one.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import time
import urllib.request

import pytest

from gpumounter_tpu.collector.usage import (ChipUsageSampler,
                                            FakeUsageProbe, FsUsageProbe)
from gpumounter_tpu.master.admission import AttachBroker, BrokerConfig
from gpumounter_tpu.master.fleet import FleetAggregator
from gpumounter_tpu.testing.sim import LiveStack, WorkerRig
from gpumounter_tpu.utils.metrics import REGISTRY


def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


# -- config knobs --------------------------------------------------------------

def test_usage_knobs_default_on_and_disable():
    from gpumounter_tpu.utils.config import Settings
    s = Settings.from_env({})
    assert s.usage_enabled is True
    assert s.usage_interval_s == 5.0
    assert s.idle_lease_s == 300.0
    assert Settings.from_env({"TPU_USAGE": "0"}).usage_enabled is False
    s = Settings.from_env({"TPU_USAGE_INTERVAL_S": "1.5",
                           "TPU_IDLE_LEASE_S": "60"})
    assert s.usage_interval_s == 1.5 and s.idle_lease_s == 60.0
    with pytest.raises(ValueError):
        Settings.from_env({"TPU_USAGE_INTERVAL_S": "0"})
    with pytest.raises(ValueError):
        Settings.from_env({"TPU_IDLE_LEASE_S": "-1"})


# -- the FsUsageProbe (real path: sysfs file, then open-fd detection) ----------

def test_fs_probe_reads_sysfs_usage_file_and_open_fds(fake_host):
    from gpumounter_tpu.device.enumerator import PyEnumerator
    # two fake chips on the fixture tree
    for i in range(2):
        with open(os.path.join(fake_host.dev_root, f"accel{i}"), "w"):
            pass
    enum = PyEnumerator(fake_host, allow_fake=True)
    chips = enum.enumerate()
    assert len(chips) == 2
    # chip 0: sysfs-style usage file (preferred source)
    sys_dir = os.path.join(fake_host.sys_root, "class", "accel",
                           "accel0", "device")
    os.makedirs(sys_dir)
    with open(os.path.join(sys_dir, "usage"), "w") as f:
        f.write("42\n")
    # chip 1: no sysfs file — open-fd detection: pid 55 holds the node
    fd_dir = os.path.join(fake_host.proc_root, "55", "fd")
    os.makedirs(fd_dir)
    os.symlink(os.path.join(fake_host.dev_root, "accel1"),
               os.path.join(fd_dir, "3"))
    probe = FsUsageProbe(fake_host, enum)
    duties = probe.sample(chips)
    assert duties[chips[0].uuid] == pytest.approx(0.42)
    assert duties[chips[1].uuid] == 1.0
    # fd closed -> idle
    os.unlink(os.path.join(fd_dir, "3"))
    assert probe.sample(chips)[chips[1].uuid] == 0.0


# -- sampler: attribution, edges, ring, gauges, /utilz -------------------------

@pytest.fixture
def usage_rig(fake_host):
    rig = WorkerRig(fake_host, n_chips=4, usage="fake")
    yield rig
    rig.close()


def test_sampler_attributes_chips_to_owner_and_counts_opens(usage_rig):
    rig = usage_rig
    outcome = rig.service.add_tpu("workload", "default", 2, True)
    assert outcome.result.name == "SUCCESS"
    uuids = [c.uuid for c in outcome.chips]
    attributed0 = REGISTRY.device_opens.value(tenant="default",
                                              outcome="attributed")
    # idle first: attribution present, nothing busy, no opens
    entry = rig.usage.sample_once()
    for uuid in uuids:
        assert entry["chips"][uuid]["owner"] == "default/workload"
        assert entry["chips"][uuid]["busy"] is False
    # busy edge: one open per chip, attributed to the owner namespace
    for uuid in uuids:
        rig.usage_probe.set_duty(uuid, 0.8)
    rig.usage.sample_once()
    assert REGISTRY.device_opens.value(
        tenant="default", outcome="attributed") == attributed0 + 2
    # still busy: no NEW opens (edge accounting, not level)
    rig.usage.sample_once()
    assert REGISTRY.device_opens.value(
        tenant="default", outcome="attributed") == attributed0 + 2
    # close + reopen: one more edge each
    for uuid in uuids:
        rig.usage_probe.set_duty(uuid, 0.0)
    rig.usage.sample_once()
    for uuid in uuids:
        rig.usage_probe.set_duty(uuid, 0.5)
    rig.usage.sample_once()
    assert REGISTRY.device_opens.value(
        tenant="default", outcome="attributed") == attributed0 + 4
    # duty gauge exports the latest observation per chip
    assert REGISTRY.chip_duty_cycle.value(chip=uuids[0]) == 0.5
    snap = rig.usage.snapshot()
    owner = snap["owners"]["default/workload"]
    assert owner["chips"] == 2 and owner["busy_chips"] == 2
    assert snap["opens"]["attributed"] >= 2
    by_uuid = {c["chip"]: c for c in snap["chips"]}
    assert by_uuid[uuids[0]]["opens"] == 2
    assert by_uuid[uuids[0]]["slave_pod"]      # held through a slave pod


def test_unattributed_busy_chip_is_flagged_and_counted(usage_rig):
    rig = usage_rig
    before = REGISTRY.device_opens.value(tenant="",
                                         outcome="unattributed")
    # a FREE chip goes busy: nobody holds a grant for it
    free_uuid = rig.sim.collector.chips[0].uuid
    rig.usage_probe.set_duty(free_uuid, 1.0)
    rig.usage.sample_once()
    assert REGISTRY.device_opens.value(
        tenant="", outcome="unattributed") == before + 1
    snap = rig.usage.snapshot()
    assert snap["unattributed_busy"] == 1
    flagged = [c for c in snap["chips"] if c.get("unattributed_busy")]
    assert [c["chip"] for c in flagged] == [free_uuid]


def test_sampler_ring_is_bounded_and_averages_window(usage_rig):
    rig = usage_rig
    rig.usage._ring = type(rig.usage._ring)(maxlen=16)   # small window
    uuid = rig.sim.collector.chips[0].uuid
    for i in range(40):
        rig.usage_probe.set_duty(uuid, 1.0 if i % 2 else 0.0)
        rig.usage.sample_once()
    snap = rig.usage.snapshot()
    assert snap["window_samples"] == 16
    assert snap["samples"] == 40
    chip = next(c for c in snap["chips"] if c["chip"] == uuid)
    assert 0.3 <= chip["avg_duty"] <= 0.7


def test_utilz_endpoint_serves_snapshot_and_disabled_stub(usage_rig):
    from gpumounter_tpu.worker.main import start_health_server
    server = start_health_server(0, usage=usage_rig.usage, ready=True)
    bare = start_health_server(0, ready=True)
    try:
        payload = _get_json(
            f"http://127.0.0.1:{server.server_port}/utilz")
        assert payload["enabled"] is True
        assert payload["interval_s"] == usage_rig.usage.interval_s
        assert _get_json(
            f"http://127.0.0.1:{bare.server_port}/utilz") == {
                "enabled": False}
    finally:
        server.shutdown()
        bare.shutdown()


# -- fleet join: per-node summary, activity map, idle list ---------------------

class _FakeLease:
    def __init__(self, tenant, priority="normal"):
        self.tenant = tenant
        self.priority = priority


def test_fleet_applies_utilz_and_lists_idle_leases():
    leases = {("default", "pod-a"): _FakeLease("teamA"),
              ("default", "pod-b"): _FakeLease("teamB")}
    fleet = FleetAggregator(lambda: {},
                            lease_lookup=lambda ns, pod:
                            leases.get((ns, pod)))
    record = type("R", (), {"node": "node-0", "utilz": None})()
    payload = {
        "enabled": True,
        "chips": [{"chip": "0", "duty": 0.9, "busy": True},
                  {"chip": "1", "duty": 0.9, "busy": True},
                  {"chip": "2", "duty": 0.0, "busy": False},
                  {"chip": "3", "duty": 0.0, "busy": False}],
        "unattributed_busy": 0,
        "owners": {
            "default/pod-a": {"chips": 2, "busy_chips": 2,
                              "avg_duty": 0.9,
                              "last_busy_unix": time.time()},
            "default/pod-b": {"chips": 2, "busy_chips": 0,
                              "avg_duty": 0.0, "last_busy_unix": None},
        },
    }
    fleet._apply_utilz(record, payload)
    assert record.utilz["chips_busy"] == 2
    assert record.utilz["chips_total"] == 4
    view = fleet._utilization_view()
    assert view["tenants"]["teamA"]["busy_chips"] == 2
    assert view["tenants"]["teamA"]["avg_duty"] == pytest.approx(0.9)
    assert view["tenants"]["teamB"]["idle_chips"] == 2
    idle = view["idle_leases"]
    assert len(idle) == 1 and idle[0]["pod"] == "pod-b"
    assert idle[0]["tenant"] == "teamB"
    activity = fleet.lease_activity()
    assert activity[("default", "pod-a")]["busy_chips"] == 2
    assert activity[("default", "pod-b")]["last_busy_unix"] is None
    # a disabled /utilz payload is ignored entirely — and CLEARS a
    # previously-scraped summary (a worker rolled to TPU_USAGE=0 must
    # not render frozen pre-rollout numbers as live data)
    record2 = type("R", (), {"node": "node-1", "utilz": None})()
    fleet._apply_utilz(record2, {"enabled": False})
    assert record2.utilz is None
    fleet._apply_utilz(record, {"enabled": False})
    assert record.utilz is None


# -- broker: idle marking + idle-aware victim preference -----------------------

def _activity(busy: bool, idle_for_s: float = 0.0):
    now = time.time()
    return {"busy_chips": 2 if busy else 0, "chips": 2,
            "duty": 0.9 if busy else 0.0,
            "first_seen_unix": now - idle_for_s,
            "last_busy_unix": now if busy else None,
            "last_seen_unix": now, "node": "node-a"}


def test_broker_marks_idle_leases_and_prefers_idle_victims():
    from gpumounter_tpu.k8s.client import FakeKubeClient
    from gpumounter_tpu.utils.events import EVENTS
    broker = AttachBroker(FakeKubeClient(), BrokerConfig(
        quotas={"teamA": 1, "teamB": 1}, quota_burst=2.0,
        idle_lease_s=5.0))
    broker._rederived = True
    # the soon-idle lease is recorded FIRST (oldest): the pre-existing
    # newest-grant-first rule alone would pick pod-a, so this pins that
    # idleness actually outranks recency
    broker.leases.record("default", "pod-b", "teamB", "normal",
                         ["2", "3"], node="node-a")
    time.sleep(0.01)
    broker.leases.record("default", "pod-a", "teamA", "normal",
                         ["0", "1"], node="node-a")
    feed = {("default", "pod-a"): _activity(busy=True),
            ("default", "pod-b"): _activity(busy=False, idle_for_s=10.0)}
    broker.bind_utilization(lambda: feed)
    broker._mark_idle_leases()
    lease_a = broker.leases.get("default", "pod-a")
    lease_b = broker.leases.get("default", "pod-b")
    assert lease_a.idle_since_unix is None
    assert lease_b.idle_since_unix is not None
    assert REGISTRY.tenant_chips_idle.value(tenant="teamB") == 2
    assert REGISTRY.tenant_chips_idle.value(tenant="teamA") == 0
    assert lease_b.to_json()["idle"] is True
    assert lease_b.to_json()["idle_s"] >= 0
    assert "idle" not in lease_a.to_json()
    events = [e for e in EVENTS.tail(64) if e["kind"] == "idle_lease"]
    assert any(e.get("pod") == "pod-b" for e in events)
    # /brokerz: idle chips surfaced per tenant, busy tenants untouched
    snap = broker.snapshot()
    assert snap["tenants"]["teamB"]["idle_chips"] == 2
    assert "idle_chips" not in snap["tenants"]["teamA"]
    # victim preference: both over quota, same priority — pod-b's grant
    # is OLDER (the newest-first tiebreak alone would pick pod-a), but
    # the idle lease goes first
    waiter = type("W", (), {"tenant": "vip", "priority": "high",
                            "namespace": "default", "pod": "vip-pod",
                            "node": "node-a", "rid": "r1"})()
    victim = broker._pick_victim(waiter)
    assert victim.pod == "pod-b"
    # busy again: the mark clears and the gauge returns to zero
    feed[("default", "pod-b")] = _activity(busy=True)
    broker._mark_idle_leases()
    assert broker.leases.get("default",
                             "pod-b").idle_since_unix is None
    assert REGISTRY.tenant_chips_idle.value(tenant="teamB") == 0


def test_idle_mark_clears_on_burst_between_scrapes_and_lost_feed():
    """An idle mark must not outlive its evidence: a chip that burst
    busy BETWEEN scrapes (last_busy advanced, instantaneous busy_chips
    still 0) drops the lease under the threshold and un-marks it, and a
    lease whose telemetry vanished entirely is un-marked too — stale
    idleness must never steer preemption."""
    from gpumounter_tpu.k8s.client import FakeKubeClient
    broker = AttachBroker(FakeKubeClient(),
                          BrokerConfig(idle_lease_s=5.0))
    broker._rederived = True
    broker.leases.record("default", "pod-i", "teamI", "normal", ["0"])
    feed = {("default", "pod-i"): _activity(busy=False,
                                            idle_for_s=10.0)}
    broker.bind_utilization(lambda: feed)
    broker._mark_idle_leases()
    lease = broker.leases.get("default", "pod-i")
    assert lease.idle_since_unix is not None
    # burst between scrapes: busy_chips 0 at the instant, but
    # last_busy_unix moved to just now -> idle_for below the threshold
    now = time.time()
    feed[("default", "pod-i")] = {
        "busy_chips": 0, "chips": 1, "duty": 0.0,
        "first_seen_unix": now - 60.0, "last_busy_unix": now - 1.0,
        "last_seen_unix": now, "node": "node-a"}
    broker._mark_idle_leases()
    assert lease.idle_since_unix is None
    # re-idle past the threshold, then the feed loses the lease
    feed[("default", "pod-i")] = _activity(busy=False, idle_for_s=10.0)
    broker._mark_idle_leases()
    assert lease.idle_since_unix is not None
    feed.clear()
    broker._mark_idle_leases()
    assert lease.idle_since_unix is None
    assert REGISTRY.tenant_chips_idle.value(tenant="teamI") == 0


def test_broker_ignores_unobserved_leases_and_short_idle():
    from gpumounter_tpu.k8s.client import FakeKubeClient
    broker = AttachBroker(FakeKubeClient(),
                          BrokerConfig(idle_lease_s=60.0))
    broker._rederived = True
    broker.leases.record("default", "pod-x", "teamX", "normal", ["0"])
    broker.leases.record("default", "pod-y", "teamY", "normal", ["1"])
    broker.bind_utilization(lambda: {
        ("default", "pod-y"): _activity(busy=False, idle_for_s=1.0)})
    broker._mark_idle_leases()
    # pod-x: no telemetry — absence of data must never read as idle;
    # pod-y: idle but under the threshold
    assert broker.leases.get("default", "pod-x").idle_since_unix is None
    assert broker.leases.get("default", "pod-y").idle_since_unix is None


def test_idle_lease_burst_triggers_one_flight_bundle(tmp_path):
    from gpumounter_tpu.k8s.client import FakeKubeClient
    from gpumounter_tpu.utils.flight import RECORDER
    RECORDER.configure(str(tmp_path), min_interval_s=0.0, settle_s=0.0)
    try:
        broker = AttachBroker(FakeKubeClient(), BrokerConfig(
            idle_lease_s=1.0))
        broker._rederived = True
        feed = {}
        for i in range(3):
            broker.leases.record("default", f"pod-{i}", f"t{i}",
                                 "normal", [str(i)])
            feed[("default", f"pod-{i}")] = _activity(busy=False,
                                                      idle_for_s=5.0)
        broker.bind_utilization(lambda: feed)
        broker._mark_idle_leases()   # 3 transitions >= the burst bar
        bundles = [n for n in os.listdir(tmp_path)
                   if "idle_lease_burst" in n]
        assert len(bundles) == 1
        with open(tmp_path / bundles[0]) as f:
            bundle = json.load(f)
        assert bundle["trigger"] == "idle_lease_burst"
    finally:
        RECORDER.configure(None)


# -- acceptance e2e: idle tenant flagged fleet-wide and preempted first --------

def test_e2e_idle_lease_flagged_and_preempted_before_busy(fake_host):
    """ISSUE 10 acceptance: two tenants hold live leases on one node;
    one goes idle. /utilz attributes per-lease utilization, /fleetz
    lists the idle lease within ONE fleet tick, doctor WARNs, and a
    high-priority waiter preempts the IDLE lease while the busy
    tenant's chips survive."""
    config = BrokerConfig(quotas={"teamA": 1, "teamB": 1, "vip": 8},
                          quota_burst=2.0, queue_timeout_s=30.0,
                          idle_lease_s=0.3)
    rig = WorkerRig(fake_host, n_chips=4, usage="fake")
    stack = LiveStack(rig, broker_config=config, shared_kube=True)
    try:
        for name in ("pod-a", "pod-b", "vip-pod"):
            pod = rig.sim.add_target_pod(name=name)
            rig.provision_container(pod)

        def attach(pod, tenant, priority="normal"):
            return _get_json(
                f"{stack.base}/addtpu/namespace/default/pod/{pod}"
                f"/tpu/2/isEntireMount/true"
                f"?tenant={tenant}&priority={priority}", timeout=60)

        # the soon-idle tenant attaches FIRST (oldest grant): the
        # newest-first victim tiebreak alone would reclaim pod-a, so
        # the preemption below proves idleness outranks recency
        body_b = attach("pod-b", "teamB")
        body_a = attach("pod-a", "teamA")
        assert body_a["result"] == "SUCCESS", body_a
        assert body_b["result"] == "SUCCESS", body_b
        # teamA computes, teamB walked away
        for uuid in body_a["device_ids"]:
            rig.usage_probe.set_duty(uuid, 0.9)
        for uuid in body_b["device_ids"]:
            rig.usage_probe.set_duty(uuid, 0.0)
        rig.usage.sample_once()

        # /utilz attributes per-lease utilization correctly
        utilz = rig.usage.snapshot()
        assert utilz["owners"]["default/pod-a"]["busy_chips"] == 2
        assert utilz["owners"]["default/pod-b"]["busy_chips"] == 0

        # ONE fleet tick lists the idle lease in /fleetz
        states = stack.gateway.fleet.tick()
        assert states == {"node-a": "fresh"}
        fleetz = _get_json(f"{stack.base}/fleetz")
        util = fleetz["utilization"]
        assert util["tenants"]["teamA"]["busy_chips"] == 2
        assert util["tenants"]["teamB"]["idle_chips"] == 2
        idle = util["idle_leases"]
        assert [i["pod"] for i in idle] == ["pod-b"]
        node_util = fleetz["nodes"]["node-a"]["utilization"]
        assert node_util["chips_busy"] == 2
        assert node_util["chips_total"] == 4

        # broker marks the lease idle once past TPU_IDLE_LEASE_S
        time.sleep(0.4)
        rig.usage.sample_once()
        stack.gateway.fleet.tick()
        stack.gateway.broker.tick()
        brokerz = _get_json(f"{stack.base}/brokerz")
        by_pod = {lease["pod"]: lease
                  for lease in brokerz["leases"]["leases"]}
        assert by_pod["pod-b"].get("idle") is True
        assert "idle" not in by_pod["pod-a"]

        # doctor WARNs on the idle lease (rc asserted non-zero, not ==1:
        # the process-global registry legitimately accumulates earlier
        # test files' counters, which may add their own checks)
        from gpumounter_tpu import cli
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = cli.main(["--master", stack.base, "doctor"])
        rendered = out.getvalue()
        assert rc != 0, rendered
        assert "WARN idle leased chips" in rendered
        assert "default/pod-b" in rendered

        # the high-priority waiter preempts the IDLE lease, not the
        # busy one
        vip = attach("vip-pod", "vip", priority="high")
        assert vip["result"] == "SUCCESS", vip
        brokerz = _get_json(f"{stack.base}/brokerz")
        held = {lease["pod"] for lease in brokerz["leases"]["leases"]}
        assert "pod-a" in held          # busy tenant untouched
        assert "pod-b" not in held      # idle tenant reclaimed
        assert "vip-pod" in held

        # tpumounterctl fleet renders the utilization column
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            cli.main(["--master", stack.base, "fleet"])
        assert "util[" in out.getvalue()
    finally:
        stack.close()


def test_usage_off_restores_pre_sampler_payloads(fake_host):
    """TPU_USAGE=0 semantics: no sampler wired — /utilz answers the
    disabled stub, /fleetz carries NO utilization section, and lease
    payloads carry no idle fields (byte-for-byte PR 9)."""
    rig = WorkerRig(fake_host, n_chips=4)          # usage=False
    stack = LiveStack(rig, broker_config=BrokerConfig(),
                      shared_kube=True)
    try:
        pod = rig.sim.add_target_pod(name="pod-z")
        rig.provision_container(pod)
        body = _get_json(
            f"{stack.base}/addtpu/namespace/default/pod/pod-z"
            f"/tpu/2/isEntireMount/true", timeout=60)
        assert body["result"] == "SUCCESS", body
        health = f"http://127.0.0.1:{stack.health_server.server_port}"
        assert _get_json(f"{health}/utilz") == {"enabled": False}
        stack.gateway.fleet.tick()
        fleetz = _get_json(f"{stack.base}/fleetz")
        assert "utilization" not in fleetz
        assert "utilization" not in fleetz["nodes"]["node-a"]
        brokerz = _get_json(f"{stack.base}/brokerz")
        for lease in brokerz["leases"]["leases"]:
            assert "idle" not in lease and "idle_s" not in lease
        for tenant in brokerz["tenants"].values():
            assert "idle_chips" not in tenant
    finally:
        stack.close()
