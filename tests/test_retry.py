"""Unit tests for the unified retry/backoff + circuit-breaker layer
(utils/retry.py) and the transport-cause classification it keys on."""

import threading

import pytest

from gpumounter_tpu.utils.errors import (CircuitOpenError, DeviceBusyError,
                                         K8sApiError,
                                         KubeletUnavailableError,
                                         MountPolicyError, PodNotFoundError)
from gpumounter_tpu.utils.retry import (CircuitBreaker, RetryBudget,
                                        RetryPolicy, call_with_retry,
                                        retryable,
                                        retryable_non_idempotent)


# -- classifier ----------------------------------------------------------------

@pytest.mark.parametrize("exc,expected", [
    (K8sApiError(429, "throttled"), True),
    (K8sApiError(500, "boom"), True),
    (K8sApiError(503, "unavailable"), True),
    (K8sApiError(0, "refused", cause="refused"), True),
    (K8sApiError(0, "timeout", cause="timeout"), True),
    (K8sApiError(400, "bad request"), False),
    (K8sApiError(404, "gone"), False),
    (K8sApiError(409, "conflict"), False),   # optimistic-concurrency loss
    (K8sApiError(410, "expired"), False),    # needs a re-LIST, not a retry
    (PodNotFoundError("ns", "p"), False),
    (KubeletUnavailableError("socket flap"), True),
    (MountPolicyError("denied"), False),
    (DeviceBusyError("0", [42]), False),
    (ValueError("a bug"), False),
])
def test_retryable_classifier(exc, expected):
    assert retryable(exc) is expected


@pytest.mark.parametrize("exc,expected", [
    # provably-never-landed failures: replay is safe even for a create
    (K8sApiError(0, "refused", cause="refused"), True),
    (K8sApiError(0, "dns", cause="dns"), True),
    (K8sApiError(429, "throttled"), True),
    # ambiguous failures: the request MAY have landed — never replayed
    (K8sApiError(0, "timeout", cause="timeout"), False),
    (K8sApiError(0, "reset", cause="reset"), False),
    (K8sApiError(500, "boom"), False),
    (K8sApiError(503, "unavailable"), False),
    (K8sApiError(409, "already exists"), False),
    (PodNotFoundError("ns", "p"), False),
])
def test_non_idempotent_classifier_only_replays_provably_unlanded(
        exc, expected):
    assert retryable_non_idempotent(exc) is expected


def test_grpc_unavailable_is_retryable_other_codes_not():
    import grpc

    class Unavailable(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

    class Internal(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.INTERNAL

    assert retryable(Unavailable()) is True
    assert retryable(Internal()) is False


def test_k8s_api_error_carries_cause_and_retry_after():
    e = K8sApiError(0, "conn refused", cause="refused")
    assert e.cause == "refused"
    assert "[refused]" in str(e)
    e = K8sApiError(429, "slow down", retry_after_s=2.5)
    assert e.retry_after_s == 2.5


# -- policy --------------------------------------------------------------------

def test_policy_delays_grow_and_cap():
    policy = RetryPolicy(base_delay_s=0.1, max_delay_s=0.5, jitter=0.0)
    assert policy.delay_s(1) == pytest.approx(0.1)
    assert policy.delay_s(2) == pytest.approx(0.2)
    assert policy.delay_s(3) == pytest.approx(0.4)
    assert policy.delay_s(4) == pytest.approx(0.5)     # capped
    assert policy.delay_s(10) == pytest.approx(0.5)


def test_policy_jitter_bounds():
    policy = RetryPolicy(base_delay_s=1.0, max_delay_s=1.0, jitter=0.25)
    for _ in range(50):
        assert 0.75 <= policy.delay_s(1) <= 1.25


def _fail_n_times(n, exc_factory, result="ok"):
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] <= n:
            raise exc_factory()
        return result
    fn.calls = calls
    return fn


FAST = RetryPolicy(max_attempts=4, base_delay_s=0.001, max_delay_s=0.002,
                   deadline_s=5.0, jitter=0.0)


def test_call_with_retry_recovers_from_transient_burst():
    fn = _fail_n_times(2, lambda: K8sApiError(500, "blip"))
    assert call_with_retry(fn, policy=FAST, target="t") == "ok"
    assert fn.calls["n"] == 3


def test_call_with_retry_gives_up_after_max_attempts():
    fn = _fail_n_times(99, lambda: K8sApiError(500, "down"))
    with pytest.raises(K8sApiError):
        call_with_retry(fn, policy=FAST, target="t")
    assert fn.calls["n"] == FAST.max_attempts


def test_call_with_retry_never_retries_deterministic_denials():
    fn = _fail_n_times(99, lambda: K8sApiError(404, "no such pod"))
    with pytest.raises(K8sApiError):
        call_with_retry(fn, policy=FAST, target="t")
    assert fn.calls["n"] == 1       # one-shot: retrying can't change a 404


def test_call_with_retry_honors_server_retry_after():
    slept = []
    fn = _fail_n_times(
        1, lambda: K8sApiError(429, "throttled", retry_after_s=0.123))
    call_with_retry(fn, policy=FAST, target="t", sleep=slept.append)
    assert slept == [0.123]         # server hint beats computed backoff


def test_call_with_retry_respects_deadline():
    # retry_after far beyond the deadline: fail now instead of sleeping
    fn = _fail_n_times(
        99, lambda: K8sApiError(429, "throttled", retry_after_s=60.0))
    policy = RetryPolicy(max_attempts=4, base_delay_s=0.001,
                         deadline_s=0.05, jitter=0.0)
    with pytest.raises(K8sApiError):
        call_with_retry(fn, policy=policy, target="t")
    assert fn.calls["n"] == 1


def test_call_with_retry_counts_attempts_metric():
    from gpumounter_tpu.utils.metrics import REGISTRY
    before = REGISTRY.retry_attempts.value(target="unit-test")
    fn = _fail_n_times(2, lambda: K8sApiError(500, "blip"))
    call_with_retry(fn, policy=FAST, target="unit-test")
    assert REGISTRY.retry_attempts.value(target="unit-test") == before + 2


def test_retry_budget_exhaustion_turns_failures_terminal():
    budget = RetryBudget(capacity=1.0, deposit_per_success=0.0)
    fn = _fail_n_times(99, lambda: K8sApiError(500, "down"))
    with pytest.raises(K8sApiError):
        call_with_retry(fn, policy=FAST, target="t", budget=budget)
    assert fn.calls["n"] == 2       # 1 retry spent the whole budget
    fn2 = _fail_n_times(99, lambda: K8sApiError(500, "down"))
    with pytest.raises(K8sApiError):
        call_with_retry(fn2, policy=FAST, target="t", budget=budget)
    assert fn2.calls["n"] == 1      # empty bucket: no retries at all


def test_retry_budget_refills_on_success():
    budget = RetryBudget(capacity=2.0, deposit_per_success=1.0)
    assert budget.try_spend() and budget.try_spend()
    assert not budget.try_spend()
    budget.deposit()
    assert budget.try_spend()


# -- circuit breaker -----------------------------------------------------------

class _Clock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


def test_breaker_opens_after_threshold_and_fails_fast():
    clock = _Clock()
    breaker = CircuitBreaker("w1", failure_threshold=3,
                             reset_timeout_s=10.0, clock=clock)
    for _ in range(3):
        breaker.allow()
        breaker.record_failure()
    with pytest.raises(CircuitOpenError) as exc:
        breaker.allow()
    assert exc.value.target == "w1"
    assert 0 < exc.value.retry_after_s <= 10.0
    breaker.record_success()   # close: the state gauge is process-global


def test_breaker_half_open_admits_single_probe_then_closes():
    clock = _Clock()
    breaker = CircuitBreaker("w1", failure_threshold=1,
                             reset_timeout_s=10.0, clock=clock)
    breaker.record_failure()
    with pytest.raises(CircuitOpenError):
        breaker.allow()
    clock.now += 11.0
    breaker.allow()                  # the probe slot
    with pytest.raises(CircuitOpenError):
        breaker.allow()              # concurrent caller: no probe stampede
    breaker.record_success()
    assert breaker.state == CircuitBreaker.CLOSED
    breaker.allow()


def test_breaker_failed_probe_reopens():
    clock = _Clock()
    breaker = CircuitBreaker("w1", failure_threshold=1,
                             reset_timeout_s=10.0, clock=clock)
    breaker.record_failure()
    clock.now += 11.0
    breaker.allow()
    breaker.record_failure()         # probe failed
    assert breaker.state == CircuitBreaker.OPEN
    with pytest.raises(CircuitOpenError):
        breaker.allow()
    breaker.record_success()   # close: the state gauge is process-global


def test_breaker_exports_state_gauge():
    from gpumounter_tpu.utils.metrics import REGISTRY
    clock = _Clock()
    breaker = CircuitBreaker("gauge-target", failure_threshold=1,
                             reset_timeout_s=10.0, clock=clock)
    assert REGISTRY.circuit_state.value(target="gauge-target") == 0
    breaker.record_failure()
    assert REGISTRY.circuit_state.value(target="gauge-target") == 2
    clock.now += 11.0
    breaker.allow()
    assert REGISTRY.circuit_state.value(target="gauge-target") == 1
    breaker.record_success()
    assert REGISTRY.circuit_state.value(target="gauge-target") == 0


def test_breaker_thread_safety_single_probe_under_contention():
    clock = _Clock()
    breaker = CircuitBreaker("w1", failure_threshold=1,
                             reset_timeout_s=1.0, clock=clock)
    breaker.record_failure()
    clock.now += 2.0
    admitted = []
    barrier = threading.Barrier(8)

    def contender():
        barrier.wait()
        try:
            breaker.allow()
            admitted.append(1)
        except CircuitOpenError:
            pass
    threads = [threading.Thread(target=contender) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(admitted) == 1        # exactly one probe
    breaker.record_success()   # close: the state gauge is process-global


# -- transport-cause classification (satellite: status-0 disambiguation) ------

def test_transport_causes_distinguish_timeout_from_refusal():
    import socket

    from gpumounter_tpu.k8s.client import _transport_cause
    assert _transport_cause(TimeoutError("timed out")) == "timeout"
    assert _transport_cause(ConnectionRefusedError()) == "refused"
    assert _transport_cause(ConnectionResetError()) == "reset"
    assert _transport_cause(socket.gaierror()) == "dns"
    assert _transport_cause("generic failure") == "unreachable"


def test_rest_client_classifies_connection_refused(tmp_path):
    """A real closed port: the one-shot layer must report status 0 with
    cause "refused" (not a bare status-0) and the retry layer must have
    re-attempted before giving up."""
    from gpumounter_tpu.k8s.client import KubeconfigKubeClient
    from gpumounter_tpu.testing.http_apiserver import write_kubeconfig
    from gpumounter_tpu.utils.metrics import REGISTRY
    # grab a port nothing listens on
    import socket as socket_mod
    s = socket_mod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cfg = write_kubeconfig(str(tmp_path / "kubeconfig"),
                           f"http://127.0.0.1:{port}")
    client = KubeconfigKubeClient(cfg)
    client.retry_policy = RetryPolicy(max_attempts=2, base_delay_s=0.01,
                                      max_delay_s=0.01, deadline_s=2.0,
                                      jitter=0.0)
    before = REGISTRY.retry_attempts.value(target="apiserver")
    with pytest.raises(K8sApiError) as exc:
        client.get_pod("default", "nope")
    assert exc.value.status == 0
    assert exc.value.cause == "refused"
    assert REGISTRY.retry_attempts.value(target="apiserver") == before + 1


def test_fake_watch_resumes_after_midstream_death():
    """A watch stream killed mid-flight resumes from the last seen
    resourceVersion: the consumer sees every event exactly once."""
    from gpumounter_tpu.k8s.client import FakeKubeClient
    from gpumounter_tpu.testing.chaos import Fault, FaultInjector
    from gpumounter_tpu.testing.sim import make_target_pod
    kube = FakeKubeClient()
    for i in range(3):
        kube.put_pod(make_target_pod(name=f"p{i}"))
    # first watch poll round passes, next two die mid-stream
    kube.faults = FaultInjector([
        Fault(op="WATCH", resource="pods", status=0, cause="reset",
              times=2, after=1)])
    events = list(kube.watch_pods("default", timeout_s=1.0))
    names = [pod["metadata"]["name"] for _, pod in events]
    assert names == ["p0", "p1", "p2"]       # no loss, no duplicates


def test_breaker_open_emits_event_and_flight_trigger(tmp_path, monkeypatch):
    """CLOSED->OPEN is a lifecycle anomaly: one ``circuit_open`` event in
    the global ring + one flight-recorder note (threshold 1 => bundle)."""
    import gpumounter_tpu.utils.flight as flight
    from gpumounter_tpu.utils.events import EVENTS
    from gpumounter_tpu.utils.flight import FlightRecorder
    rec = FlightRecorder(str(tmp_path), settle_s=0.0)
    monkeypatch.setattr(flight, "RECORDER", rec)
    cursor = EVENTS.emit("test_marker")
    breaker = CircuitBreaker("evt-target", failure_threshold=1,
                             reset_timeout_s=10.0, clock=_Clock())
    breaker.record_failure()
    fresh, _, _ = EVENTS.since(cursor)
    opened = [e for e in fresh if e["kind"] == "circuit_open"]
    assert len(opened) == 1
    assert opened[0]["attrs"]["target"] == "evt-target"
    assert len(list(tmp_path.glob("flight-*.json"))) == 1
    breaker.record_success()   # close: the state gauge is process-global


def test_scrape_breaker_open_is_silent(tmp_path, monkeypatch):
    """The fleet's scrape breaker opening is a telemetry miss, already
    surfaced as the node's ``stale`` record — it must not write an
    anomaly bundle or emit ``circuit_open`` into the event ring."""
    import gpumounter_tpu.utils.flight as flight
    from gpumounter_tpu.master.fleet import _ScrapeBreaker
    from gpumounter_tpu.utils.events import EVENTS
    from gpumounter_tpu.utils.flight import FlightRecorder
    rec = FlightRecorder(str(tmp_path), settle_s=0.0)
    monkeypatch.setattr(flight, "RECORDER", rec)
    cursor = EVENTS.emit("test_marker")
    breaker = _ScrapeBreaker("node-9", failure_threshold=1,
                             reset_timeout_s=10.0, clock=_Clock())
    breaker.record_failure()
    assert breaker.state == CircuitBreaker.OPEN
    fresh, _, _ = EVENTS.since(cursor)
    assert [e for e in fresh if e["kind"] == "circuit_open"] == []
    assert list(tmp_path.glob("flight-*.json")) == []
    breaker.record_success()


def test_breaker_announces_outage_once_not_per_failed_probe(tmp_path,
                                                            monkeypatch):
    """A target down for an hour re-opens on every failed half-open probe;
    only the RISING edge is announced — the ring must not fill with
    duplicate circuit_open events (nor the flight dir with bundles) while
    one outage persists. Recovery re-arms the announcement."""
    import gpumounter_tpu.utils.flight as flight
    from gpumounter_tpu.utils.events import EVENTS
    from gpumounter_tpu.utils.flight import FlightRecorder
    rec = FlightRecorder(str(tmp_path), min_interval_s=0.0, settle_s=0.0)
    monkeypatch.setattr(flight, "RECORDER", rec)
    clock = _Clock()
    cursor = EVENTS.emit("test_marker")
    breaker = CircuitBreaker("probe-target", failure_threshold=1,
                             reset_timeout_s=10.0, clock=clock)
    breaker.record_failure()                 # CLOSED -> OPEN: announced
    for _ in range(3):                       # three failed probes
        clock.now += 11.0
        breaker.allow()
        breaker.record_failure()             # HALF_OPEN -> OPEN: silent
    fresh, _, _ = EVENTS.since(cursor)
    assert len([e for e in fresh if e["kind"] == "circuit_open"]) == 1
    assert len(list(tmp_path.glob("flight-*.json"))) == 1
    # recovery then a NEW outage announces again
    clock.now += 11.0
    breaker.allow()
    breaker.record_success()
    breaker.record_failure()
    fresh, _, _ = EVENTS.since(cursor)
    assert len([e for e in fresh if e["kind"] == "circuit_open"]) == 2
    breaker.record_success()   # close: the state gauge is process-global
