"""cgroup layer tests (ref analog: cgroup_test.go, but against tmp fixture
trees instead of a live node)."""

import os

import pytest

from gpumounter_tpu.actuation.cgroup import (CgroupDeviceController,
                                             CgroupResolver,
                                             detect_cgroup_version)
from gpumounter_tpu.device.fake import make_chips
from gpumounter_tpu.k8s import objects
from gpumounter_tpu.utils.errors import CgroupError

UID = "a1b2c3d4-1111-2222-3333-444455556666"


def mk_pod(qos_reported=None, qos_spec="guaranteed"):
    pod = {
        "metadata": {"name": "train-pod", "namespace": "default", "uid": UID},
        "spec": {"containers": [{"name": "main", "resources": {}}]},
        "status": {"containerStatuses": [
            {"name": "main",
             "containerID": "containerd://" + "ab" * 32}]},
    }
    if qos_reported:
        pod["status"]["qosClass"] = qos_reported
    if qos_spec == "guaranteed":
        pod["spec"]["containers"][0]["resources"] = {
            "limits": {"cpu": "1", "memory": "1Gi"},
            "requests": {"cpu": "1", "memory": "1Gi"}}
    elif qos_spec == "burstable":
        pod["spec"]["containers"][0]["resources"] = {
            "requests": {"cpu": "1"}}
    return pod


# -- QoS computation (ref cgroup.go:177-237) ----------------------------------

def test_qos_guaranteed():
    assert objects.compute_qos_class(mk_pod()) == objects.QOS_GUARANTEED


def test_qos_burstable():
    assert objects.compute_qos_class(mk_pod(qos_spec="burstable")) == \
        objects.QOS_BURSTABLE


def test_qos_best_effort():
    assert objects.compute_qos_class(mk_pod(qos_spec="none")) == \
        objects.QOS_BEST_EFFORT


def test_qos_prefers_kubelet_reported():
    pod = mk_pod(qos_reported="Burstable", qos_spec="guaranteed")
    assert objects.qos_class(pod) == "Burstable"


# -- path rendering (ref cgroup.go:52-113) ------------------------------------

def test_cgroupfs_paths_per_qos():
    r = CgroupResolver("cgroupfs")
    assert r.pod_cgroup(mk_pod(qos_reported="Guaranteed")) == f"kubepods/pod{UID}"
    assert r.pod_cgroup(mk_pod(qos_reported="Burstable")) == \
        f"kubepods/burstable/pod{UID}"
    assert r.pod_cgroup(mk_pod(qos_reported="BestEffort")) == \
        f"kubepods/besteffort/pod{UID}"


def test_cgroupfs_container_path():
    r = CgroupResolver("cgroupfs")
    cid = "docker://" + "cd" * 32
    assert r.container_cgroup(mk_pod(qos_reported="Guaranteed"), cid) == \
        f"kubepods/pod{UID}/{'cd' * 32}"


def test_systemd_paths_per_qos():
    r = CgroupResolver("systemd")
    uid_r = UID.replace("-", "_")
    assert r.pod_cgroup(mk_pod(qos_reported="Guaranteed")) == \
        f"kubepods.slice/kubepods-pod{uid_r}.slice"
    assert r.pod_cgroup(mk_pod(qos_reported="Burstable")) == \
        (f"kubepods.slice/kubepods-burstable.slice/"
         f"kubepods-burstable-pod{uid_r}.slice")


def test_systemd_scope_prefixes_by_runtime():
    r = CgroupResolver("systemd")
    pod = mk_pod(qos_reported="Guaranteed")
    base = r.pod_cgroup(pod)
    hexid = "ef" * 32
    assert r.container_cgroup(pod, f"containerd://{hexid}") == \
        f"{base}/cri-containerd-{hexid}.scope"
    assert r.container_cgroup(pod, f"docker://{hexid}") == \
        f"{base}/docker-{hexid}.scope"
    assert r.container_cgroup(pod, f"cri-o://{hexid}") == \
        f"{base}/crio-{hexid}.scope"
    # bare id assumes GKE containerd
    assert r.container_cgroup(pod, hexid) == \
        f"{base}/cri-containerd-{hexid}.scope"


def test_bad_driver_rejected():
    with pytest.raises(CgroupError):
        CgroupResolver("bogus")


# -- version detection ---------------------------------------------------------

def test_detect_v2(tmp_path):
    open(tmp_path / "cgroup.controllers", "w").close()
    assert detect_cgroup_version(str(tmp_path)) == 2


def test_detect_v1(tmp_path):
    assert detect_cgroup_version(str(tmp_path)) == 1


# -- v1 device permission writes (ref cgroup.go:143-169) -----------------------

@pytest.fixture
def v1_setup(fake_host):
    pod = mk_pod(qos_reported="Guaranteed")
    ctrl = CgroupDeviceController(fake_host, driver="cgroupfs", version=1)
    cid = "containerd://" + "ab" * 32
    cdir = ctrl.container_dir(pod, cid)
    os.makedirs(cdir)
    return pod, ctrl, cid, cdir


def test_v1_allow_write(v1_setup):
    pod, ctrl, cid, cdir = v1_setup
    chips = make_chips(2, major=120)
    ctrl.sync_device_access(pod, cid, chips)
    # append-mode fixture file preserves every grant (kernel-equivalent:
    # each write() is an operation either way)
    content = open(os.path.join(cdir, "devices.allow")).read()
    assert content.splitlines() == ["c 120:0 rw", "c 120:1 rw"]


def test_v1_deny_write(v1_setup):
    pod, ctrl, cid, cdir = v1_setup
    chips = make_chips(2, major=120)
    ctrl.revoke_device_access(pod, cid, [chips[0]], [chips[1]])
    assert open(os.path.join(cdir, "devices.deny")).read().splitlines() \
        == ["c 120:0 rw"]


def test_v1_missing_cgroup_raises(fake_host):
    ctrl = CgroupDeviceController(fake_host, driver="cgroupfs", version=1)
    with pytest.raises(CgroupError):
        ctrl.sync_device_access(mk_pod(qos_reported="Guaranteed"),
                                "containerd://" + "ab" * 32, make_chips(1))


def test_get_pids(v1_setup):
    pod, ctrl, cid, cdir = v1_setup
    with open(os.path.join(cdir, "cgroup.procs"), "w") as f:
        f.write("100\n200\n300\n")
    assert ctrl.get_pids(pod, cid) == [100, 200, 300]


def test_get_pids_missing_raises(v1_setup):
    pod, ctrl, cid, _ = v1_setup
    with pytest.raises(CgroupError):
        ctrl.get_pids(pod, "containerd://" + "00" * 32)


# -- v2 path: BPF sync wiring (gate faked; kernel attach needs privileges) -----

class RecordingGate:
    def __init__(self):
        self.calls = []
        self.rules = []

    def sync(self, cgroup_dir, rules):
        self.calls.append((cgroup_dir, len(rules)))
        self.rules.append(list(rules))
        return 1


def give_live_pid(fake_host, cdir, pid=4242, dev_nodes=()):
    """Fixture container: one live PID whose /proc/<pid>/root/dev holds
    ``dev_nodes`` as (name, major, minor) fake device files (regular files
    with .majmin sidecars — the representation container_device_rules
    accepts unprivileged)."""
    with open(os.path.join(cdir, "cgroup.procs"), "w") as f:
        f.write(f"{pid}\n")
    droot = os.path.join(fake_host.proc_root, str(pid), "root", "dev")
    os.makedirs(droot, exist_ok=True)
    for name, major, minor in dev_nodes:
        path = os.path.join(droot, name)
        open(path, "w").close()
        with open(path + ".majmin", "w") as f:
            f.write(f"{major}:{minor}")
    return pid


@pytest.fixture
def v2_setup(fake_host):
    pod = mk_pod(qos_reported="Guaranteed")
    gate = RecordingGate()
    ctrl = CgroupDeviceController(fake_host, driver="systemd", version=2,
                                  bpf_gate=gate)
    cid = "containerd://" + "ab" * 32
    cdir = ctrl.container_dir(pod, cid)
    os.makedirs(cdir)
    return pod, ctrl, gate, cid, cdir


def test_v2_sync_passes_full_ruleset(fake_host, v2_setup):
    from gpumounter_tpu.actuation.bpf import CONTAINER_DEFAULT_RULES
    pod, ctrl, gate, cid, cdir = v2_setup
    give_live_pid(fake_host, cdir)
    chips = make_chips(4)
    ctrl.sync_device_access(pod, cid, chips)
    assert gate.calls == [(cdir, len(CONTAINER_DEFAULT_RULES) + 4)]
    # detach back to 1 chip re-syncs with defaults+1
    ctrl.revoke_device_access(pod, cid, chips[1:], chips[:1])
    assert gate.calls[-1] == (cdir, len(CONTAINER_DEFAULT_RULES) + 1)


def test_v2_missing_cgroup_raises(fake_host):
    ctrl = CgroupDeviceController(fake_host, driver="systemd", version=2,
                                  bpf_gate=RecordingGate())
    with pytest.raises(CgroupError):
        ctrl.sync_device_access(mk_pod(qos_reported="Guaranteed"),
                                "containerd://" + "ab" * 32, make_chips(1))


def test_v2_revoke_excludes_detached_chip_still_in_dev(fake_host, v2_setup):
    """The detach-time /dev scan sees the chip being detached (nodes are
    removed only after the cgroup sync); the composed program must NOT
    re-grant it via the observed rules."""
    pod, ctrl, gate, cid, cdir = v2_setup
    chips = make_chips(2, major=120)
    # container /dev still holds BOTH chips plus an unrelated runtime grant
    give_live_pid(fake_host, cdir, dev_nodes=[
        ("accel0", 120, 0), ("accel1", 120, 1), ("fuse", 10, 229)])
    ctrl.revoke_device_access(pod, cid, [chips[0]], [chips[1]])
    majmins = {(r.major, r.minor) for r in gate.rules[-1]}
    assert (120, 0) not in majmins          # detached chip really revoked
    assert (120, 1) in majmins              # remaining chip kept
    assert (10, 229) in majmins             # unrelated runtime grant kept


def test_v2_revoke_keeps_shared_companion(fake_host, v2_setup):
    """A companion node (e.g. /dev/vfio/vfio) shared with a remaining chip
    must survive the exclusion."""
    from gpumounter_tpu.device.model import CompanionNode, TPUChip
    pod, ctrl, gate, cid, cdir = v2_setup
    comp = CompanionNode("/dev/vfio/vfio", 10, 196)
    chips = [TPUChip(index=i, device_path=f"/dev/vfio/{i}", major=511,
                     minor=i, uuid=str(i), companions=(comp,))
             for i in range(2)]
    give_live_pid(fake_host, cdir, dev_nodes=[
        ("vfio0", 511, 0), ("vfio1", 511, 1), ("vfio", 10, 196)])
    ctrl.revoke_device_access(pod, cid, [chips[0]], [chips[1]])
    majmins = {(r.major, r.minor) for r in gate.rules[-1]}
    assert (511, 0) not in majmins
    assert (511, 1) in majmins
    assert (10, 196) in majmins             # shared companion survives


def test_v2_sync_fails_closed_without_pid_or_cache(fake_host, v2_setup):
    pod, ctrl, gate, cid, cdir = v2_setup
    # cgroup exists but has no cgroup.procs at all
    with pytest.raises(CgroupError, match="fail closed"):
        ctrl.sync_device_access(pod, cid, make_chips(1))
    assert gate.calls == []                 # nothing reached the gate


def test_v2_sync_unreadable_dev_is_not_an_empty_baseline(fake_host, v2_setup):
    """A PID whose /proc entry exists but whose root/dev is gone (exited
    between liveness check and scan) must NOT be treated as observed-empty:
    with no cache the sync fails closed instead of silently revoking."""
    pod, ctrl, gate, cid, cdir = v2_setup
    with open(os.path.join(cdir, "cgroup.procs"), "w") as f:
        f.write("4242\n")
    os.makedirs(os.path.join(fake_host.proc_root, "4242"))  # no root/dev
    with pytest.raises(CgroupError, match="fail closed"):
        ctrl.sync_device_access(pod, cid, make_chips(1))
    assert gate.calls == []
    assert ctrl._observed_cache == {}       # nothing poisoned the cache


def test_v2_sync_falls_back_to_cached_baseline(fake_host, v2_setup):
    """PIDs vanish mid-lifecycle: the runtime-granted extra rule observed at
    mount time survives the later sync via the cached baseline."""
    pod, ctrl, gate, cid, cdir = v2_setup
    chips = make_chips(2, major=120)
    pid = give_live_pid(fake_host, cdir, dev_nodes=[("fuse", 10, 229)])
    ctrl.sync_device_access(pod, cid, chips)
    assert (10, 229) in {(r.major, r.minor) for r in gate.rules[-1]}
    # all processes exit: cgroup.procs empties, /proc entry disappears
    import shutil
    shutil.rmtree(os.path.join(fake_host.proc_root, str(pid)))
    with open(os.path.join(cdir, "cgroup.procs"), "w") as f:
        f.write("")
    ctrl.revoke_device_access(pod, cid, [chips[0]], [chips[1]])
    majmins = {(r.major, r.minor) for r in gate.rules[-1]}
    assert (10, 229) in majmins             # runtime grant preserved
    assert (120, 0) not in majmins          # detached chip still revoked
    assert (120, 1) in majmins


def test_v1_allow_covers_companions(fake_host):
    from gpumounter_tpu.device.model import CompanionNode, TPUChip
    pod = mk_pod(qos_reported="Guaranteed")
    ctrl = CgroupDeviceController(fake_host, driver="cgroupfs", version=1)
    cid = "containerd://" + "ab" * 32
    cdir = ctrl.container_dir(pod, cid)
    os.makedirs(cdir)
    comp = CompanionNode("/dev/vfio/vfio", 10, 196)
    chips = [TPUChip(index=i, device_path=f"/dev/vfio/{i}", major=511,
                     minor=i, uuid=str(i), companions=(comp,))
             for i in range(2)]
    ctrl.sync_device_access(pod, cid, chips)
    allowed = open(os.path.join(cdir, "devices.allow")).read().splitlines()
    # both chips AND the shared vfio companion get grants (deduped)
    assert allowed == ["c 511:0 rw", "c 10:196 rw", "c 511:1 rw"]
    # removing chip0 while chip1 remains must NOT deny the shared companion
    ctrl.revoke_device_access(pod, cid, [chips[0]], [chips[1]])
    assert open(os.path.join(cdir, "devices.deny")).read().splitlines() \
        == ["c 511:0 rw"]


def test_v1_batch_issues_one_write_syscall_per_rule(fake_host):
    """Kernel contract: devices.allow/deny parse ONE rule per write(2) —
    the batched writer must flush per entry, never coalesce the batch
    into a single buffered write (the kernel would silently drop every
    rule after the first newline)."""
    import builtins
    from gpumounter_tpu.device.model import CompanionNode, TPUChip
    pod = mk_pod(qos_reported="Guaranteed")
    ctrl = CgroupDeviceController(fake_host, driver="cgroupfs", version=1)
    cid = "containerd://" + "ab" * 32
    cdir = ctrl.container_dir(pod, cid)
    os.makedirs(cdir)
    comp = CompanionNode("/dev/vfio/vfio", 10, 196)
    chips = [TPUChip(index=i, device_path=f"/dev/vfio/{i}", major=511,
                     minor=i, uuid=str(i), companions=(comp,))
             for i in range(3)]

    flushed_writes: list[str] = []
    real_open = builtins.open

    def spying_open(path, mode="r", *args, **kwargs):
        f = real_open(path, mode, *args, **kwargs)
        if not (str(path).endswith("devices.allow") and "a" in mode):
            return f
        buffered: list[str] = []
        real_write, real_flush = f.write, f.flush

        class Spy:
            def write(self, data):
                buffered.append(data)
                return real_write(data)

            def flush(self):
                # one flush = at most one rule reaches the kernel intact
                flushed_writes.append("".join(buffered))
                buffered.clear()
                return real_flush()

            def __enter__(self):
                return self

            def __exit__(self, *exc):
                if buffered:            # unflushed residue would coalesce
                    flushed_writes.append("".join(buffered))
                f.close()

            def __getattr__(self, name):
                return getattr(f, name)

        return Spy()

    builtins.open = spying_open
    try:
        ctrl.sync_device_access(pod, cid, chips)
    finally:
        builtins.open = real_open
    # 3 chips + 1 shared companion = 4 rules, each its own write(2)
    assert len(flushed_writes) == 4, flushed_writes
    for chunk in flushed_writes:
        assert chunk.count("\n") == 1, \
            f"coalesced multi-rule write would be truncated by the " \
            f"kernel: {chunk!r}"
