"""Crash-safe multi-process re-federation (ISSUE 15).

Master side: the re-federation barrier (master/slicetxn.py) — armed on
every mesh-generation bump (and a fresh slice's commit), joined by
members over ``POST /slice/barrier``, completing into a federation plan
(ordered membership = process ids, coordinator = member 0's address);
stale-generation joins refused, incomplete barriers superseded by the
next generation, persistence + lazy re-arm, stuck-barrier surfacing in
/slicez, doctor and `tpumounterctl slice status`.

Member side + acceptance: REAL subprocess members (CPU backend, gloo
collectives, 2 virtual devices each) ride ``POST /slice/resize`` 2→4→2
hosts with the loss trajectory and step counter intact, and a SIGKILLed
member mid-resize leaves the barrier stuck until the control plane
moves the generation past it — survivors roll back to the last-good
checkpoint and re-form under a re-elected coordinator.
"""

import contextlib
import io
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from gpumounter_tpu import cli
from gpumounter_tpu.master.admission import BrokerConfig
from gpumounter_tpu.testing.chaos import (assert_checkpoint_invariants,
                                          assert_slice_invariants)
from gpumounter_tpu.utils.config import HostPaths
from gpumounter_tpu.utils.events import EVENTS

jax = pytest.importorskip("jax")


def _host(tmp_path, i):
    base = tmp_path / f"node{i}"
    for sub in ("dev", "proc", "sys/fs/cgroup"):
        (base / sub).mkdir(parents=True)
    return HostPaths(dev_root=str(base / "dev"),
                     proc_root=str(base / "proc"),
                     sys_root=str(base / "sys"),
                     cgroup_root=str(base / "sys" / "fs" / "cgroup"),
                     kubelet_socket=str(base / "pr" / "kubelet.sock"))


def _post(url, obj):
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 method="POST")
    try:
        resp = urllib.request.urlopen(req, timeout=60)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _target(n, tpus=2, members=None):
    pods = members if members is not None else list(range(n))
    return {"pods": [{"namespace": "default", "pod": f"workload-{i}"}
                     for i in pods], "tpusPerHost": tpus}


def _stack(tmp_path, hosts=2, **kw):
    from gpumounter_tpu.testing.sim import MultiNodeStack
    return MultiNodeStack([_host(tmp_path, i) for i in range(hosts)],
                          n_chips=2, **kw)


def _join(base, group, gen, member, address="127.0.0.1:1"):
    return _post(f"{base}/slice/barrier",
                 {"group": group, "generation": gen,
                  "member": member, "address": address})


# ---------------------------------------------------------------------------
# master-side barrier protocol
# ---------------------------------------------------------------------------

def test_slice_attach_arms_generation_one_barrier(tmp_path):
    stack = _stack(tmp_path, hosts=2)
    try:
        status, body = _post(f"{stack.base}/addtpuslice", _target(2))
        assert status == 200, body
        group = body["group"]
        status, barrier = _get(
            f"{stack.base}/slice/barrier?group={group}")
        assert status == 200
        assert barrier["generation"] == 1
        assert barrier["expected"] == 2
        assert barrier["complete"] is False
        assert barrier["missing"] == ["default/workload-0",
                                      "default/workload-1"]
        assert barrier["stuck"] is False
        # the waiting barrier renders in /slicez (and nowhere else: a
        # completed one vanishes, keeping pre-barrier payloads intact)
        _, slicez = _get(f"{stack.base}/slicez")
        assert slicez["groups"][group]["barrier"]["expected"] == 2
        assert slicez["stuck_barriers"] == 0
    finally:
        stack.close()


def test_barrier_completes_into_plan_and_refuses_stale(tmp_path):
    stack = _stack(tmp_path, hosts=2)
    try:
        _, body = _post(f"{stack.base}/addtpuslice", _target(2))
        group = body["group"]
        status, out = _join(stack.base, group, 1, "default/workload-0",
                            "127.0.0.1:4000")
        assert status == 200 and out["complete"] is False
        # joining is idempotent: a re-join refreshes the address
        status, out = _join(stack.base, group, 1, "default/workload-0",
                            "127.0.0.1:4001")
        assert status == 200 and len(out["joined"]) == 1
        status, out = _join(stack.base, group, 1, "default/workload-1",
                            "127.0.0.1:5000")
        assert status == 200 and out["complete"] is True
        plan = out["plan"]
        # ordered membership IS the process-id assignment; coordinator
        # = member 0's LAST proposed address
        assert plan["members"] == ["default/workload-0",
                                   "default/workload-1"]
        assert plan["num_processes"] == 2
        assert plan["coordinator"] == "127.0.0.1:4001"
        # resize bumps to generation 2 → the old generation is refused
        status, body = _post(f"{stack.base}/slice/resize", {
            "pods": [{"namespace": "default", "pod": "workload-0"}]})
        assert status == 200 and body["generation"] == 2
        status, out = _join(stack.base, group, 1, "default/workload-0")
        assert status == 409 and out["result"] == "StaleGeneration"
        assert out["current"] == 2
        # a FUTURE generation is unknown, not stale
        status, out = _join(stack.base, group, 7, "default/workload-0")
        assert status == 409 and out["result"] == "UnknownGeneration"
        # a pod resized out of the membership is refused by name
        status, out = _join(stack.base, group, 2, "default/workload-1")
        assert status == 403 and out["result"] == "NotAMember"
        # and garbage is a 400, not a crash
        status, out = _post(f"{stack.base}/slice/barrier",
                            {"group": group, "generation": "x",
                             "member": "default/workload-0"})
        assert status == 400
    finally:
        stack.close()


def test_new_generation_supersedes_incomplete_barrier(tmp_path):
    from gpumounter_tpu.utils.metrics import REGISTRY
    stack = _stack(tmp_path, hosts=3)
    try:
        _, body = _post(f"{stack.base}/addtpuslice", _target(2))
        group = body["group"]
        status, _ = _join(stack.base, group, 1, "default/workload-0")
        assert status == 200
        superseded0 = REGISTRY.slice_barriers.series().get(
            (("transition", "superseded"),), 0.0)
        # limit=-1: an untruncated snapshot, so seq is the ring's TRUE
        # newest (a truncated page's seq points at the page end — a
        # full suite's ring would hand back a cursor deep in the past)
        events0 = EVENTS.snapshot(limit=-1)["seq"]
        _, body = _post(f"{stack.base}/slice/resize", _target(3))
        assert body["generation"] == 2
        _, barrier = _get(f"{stack.base}/slice/barrier?group={group}")
        assert barrier["generation"] == 2
        assert barrier["joined"] == []          # joins restart
        assert barrier["expected"] == 3
        # the supersede crossed the observability seam: metric + event
        superseded1 = REGISTRY.slice_barriers.series().get(
            (("transition", "superseded"),), 0.0)
        assert superseded1 == superseded0 + 1
        tail = [e for e in EVENTS.snapshot(since=events0,
                                           limit=-1)["events"]
                if e["kind"] == "slice_barrier"
                and e["attrs"].get("group") == group
                and e["attrs"].get("transition") == "superseded"]
        assert len(tail) == 1 and tail[0]["attrs"]["generation"] == 1
        assert tail[0]["attrs"]["superseded_by"] == 2
    finally:
        stack.close()


def test_stuck_barrier_surfaces_in_slicez_doctor_and_cli(tmp_path):
    stack = _stack(
        tmp_path, hosts=2,
        broker_config=BrokerConfig(resize_barrier_timeout_s=0.05))
    try:
        _, body = _post(f"{stack.base}/addtpuslice", _target(2))
        group = body["group"]
        _join(stack.base, group, 1, "default/workload-0")
        time.sleep(0.1)
        _, barrier = _get(f"{stack.base}/slice/barrier?group={group}")
        assert barrier["stuck"] is True
        assert barrier["missing"] == ["default/workload-1"]
        _, slicez = _get(f"{stack.base}/slicez")
        assert slicez["stuck_barriers"] == 1
        # doctor WARNs, naming the missing member
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = cli.main(["--master", stack.base, "doctor"])
        assert rc == 1, out.getvalue()
        assert "barrier" in out.getvalue()
        assert "default/workload-1" in out.getvalue()
        # slice status renders it and exits non-zero
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = cli.main(["--master", stack.base, "slice", "status"])
        assert rc == 1
        assert "STUCK" in out.getvalue()
        assert "default/workload-1" in out.getvalue()
    finally:
        stack.close()


def test_barrier_rearms_lazily_after_state_loss(tmp_path):
    """Coordinator death without a store: the restarted master has no
    barrier state, but a member's join lazily re-arms one at the
    group's CURRENT generation from the lease table — the control
    plane stays the source of truth, not any process's memory."""
    stack = _stack(tmp_path, hosts=2)
    try:
        _, body = _post(f"{stack.base}/addtpuslice", _target(2))
        group = body["group"]
        manager = stack.gateway.slices
        with manager._lock:               # "restart": in-memory loss
            manager._barriers.clear()
        status, out = _join(stack.base, group, 1, "default/workload-1")
        assert status == 200
        assert out["generation"] == 1
        assert out["joined"] == ["default/workload-1"]
        # a stale join against the re-armed barrier is still refused
        status, out = _join(stack.base, group, 0, "default/workload-1")
        assert status == 409
    finally:
        stack.close()


def test_barrier_record_rearms_from_the_store(tmp_path):
    """A failed-over leader re-arms persisted barriers with an empty
    joined set (adopt_barriers) — and ignores records older than what
    it already carries."""
    from gpumounter_tpu.master.store import SliceBarrierRecord
    stack = _stack(tmp_path, hosts=2)
    try:
        _, body = _post(f"{stack.base}/addtpuslice", _target(2))
        group = body["group"]
        manager = stack.gateway.slices
        record = SliceBarrierRecord(
            group=group, generation=5,
            members=["default/workload-0", "default/workload-1"],
            created_unix=time.time())
        assert manager.adopt_barriers([record]) == 1
        _, barrier = _get(f"{stack.base}/slice/barrier?group={group}")
        assert barrier["generation"] == 5 and barrier["joined"] == []
        # an OLDER record does not clobber the newer in-memory barrier
        stale = SliceBarrierRecord(
            group=group, generation=2,
            members=["default/workload-0"], created_unix=time.time())
        assert manager.adopt_barriers([stale]) == 0
        _, barrier = _get(f"{stack.base}/slice/barrier?group={group}")
        assert barrier["generation"] == 5
        roundtrip = SliceBarrierRecord.from_json(record.to_json())
        assert roundtrip == record
        # a COMPLETED record restores its frozen plan verbatim: members
        # still polling (or blocked in initialize waiting on one that
        # is) must receive the SAME plan, never a fresh barrier nobody
        # can complete
        done = SliceBarrierRecord(
            group=group, generation=6,
            members=["default/workload-0", "default/workload-1"],
            created_unix=time.time(),
            plan={"coordinator": "127.0.0.1:7777", "num_processes": 2,
                  "members": ["default/workload-0",
                              "default/workload-1"]},
            completed_unix=time.time())
        assert manager.adopt_barriers([done]) == 1
        _, barrier = _get(f"{stack.base}/slice/barrier?group={group}")
        assert barrier["complete"] is True
        assert barrier["plan"]["coordinator"] == "127.0.0.1:7777"
    finally:
        stack.close()


def test_teardown_retires_the_barrier(tmp_path):
    stack = _stack(tmp_path, hosts=2)
    try:
        _, body = _post(f"{stack.base}/addtpuslice", _target(2))
        group = body["group"]
        _, body = _post(f"{stack.base}/removetpuslice", _target(2))
        stack.gateway.slices.export_gauges()
        status, _ = _get(f"{stack.base}/slice/barrier?group={group}")
        assert status == 404
        # a member mid-refederation when the group vanished gets the
        # clean resized-out exit, not a transport-error crash
        from gpumounter_tpu.jaxcheck import federation as fed
        client = fed.BarrierClient(stack.base, group,
                                   "default/workload-0")
        with pytest.raises(fed.MembershipRefusedError):
            client.join(1, "127.0.0.1:4000")
        assert_slice_invariants(stack.gateway.broker,
                                [r.sim for r in stack.rigs],
                                kube=stack.master_kube)
    finally:
        stack.close()


def test_orphan_adopted_barrier_is_swept(tmp_path):
    """A barrier adopted for a group that no longer exists (torn down
    before the failover) must be retired by the gauge sweep — not page
    the stuck alert forever for a ghost."""
    from gpumounter_tpu.master.store import SliceBarrierRecord
    from gpumounter_tpu.utils.metrics import REGISTRY
    stack = _stack(tmp_path, hosts=2)
    try:
        manager = stack.gateway.slices
        ghost = SliceBarrierRecord(
            group="txn-ghost", generation=4,
            members=["default/gone-0", "default/gone-1"],
            created_unix=time.time())
        assert manager.adopt_barriers([ghost]) == 1
        # the arm's own gauge pass already swept it: a ghost barrier
        # never outlives the very call that adopted it
        manager.export_gauges()
        status, _ = _get(f"{stack.base}/slice/barrier?group=txn-ghost")
        assert status == 404
        assert REGISTRY.slice_barriers_incomplete.value() == 0
    finally:
        stack.close()


# ---------------------------------------------------------------------------
# the member side, in-process (fast paths of jaxcheck/federation.py)
# ---------------------------------------------------------------------------

def test_barrier_client_typed_refusals(tmp_path):
    from gpumounter_tpu.jaxcheck import federation as fed
    stack = _stack(tmp_path, hosts=2)
    try:
        _, body = _post(f"{stack.base}/addtpuslice", _target(2))
        group = body["group"]
        client = fed.BarrierClient(stack.base, group,
                                   "default/workload-0")
        out = client.join(1, "127.0.0.1:4000")
        assert out["complete"] is False
        _, body = _post(f"{stack.base}/slice/resize", {
            "pods": [{"namespace": "default", "pod": "workload-0"}]})
        assert body["generation"] == 2
        with pytest.raises(fed.StaleGenerationError) as info:
            client.join(1, "127.0.0.1:4000")
        assert info.value.current == 2
        other = fed.BarrierClient(stack.base, group,
                                  "default/workload-1")
        with pytest.raises(fed.MembershipRefusedError):
            other.join(2, "127.0.0.1:5000")
        # a generation AHEAD of the barrier is typed too (the member
        # keeps its target and re-joins; never a transport OSError)
        with pytest.raises(fed.UnknownGenerationError):
            client.join(9, "127.0.0.1:4000")
        # wait() on a superseded target raises the typed retarget too
        with pytest.raises(fed.StaleGenerationError):
            client.wait(1, timeout_s=1.0)
        # and an incomplete barrier times out rather than hanging
        with pytest.raises(fed.BarrierTimeoutError):
            client.wait(2, timeout_s=0.3)
    finally:
        stack.close()


def test_single_process_crash_between_drain_and_restore_resumes(
        tmp_path):
    """The sole-surviving-copy scenario: a harness crashes after the
    sharded drain committed but before restore. The next boot
    (start(resume=True) / MemberRunner's resume path) restores the
    checkpoint instead of resetting the trajectory."""
    from gpumounter_tpu.jaxcheck import federation as fed
    from gpumounter_tpu.jaxcheck import model as model_lib
    from gpumounter_tpu.jaxcheck import train as train_lib
    import numpy as np
    cfg = model_lib.ModelConfig(vocab=64, d_model=32, n_heads=4,
                                n_layers=1, d_ff=64)
    root = str(tmp_path / "ckpt")
    signal_state = {"gen": 1, "chips": 4}

    def harness():
        return fed.FederatedElasticHarness(
            cfg, lambda: signal_state["gen"],
            lambda: signal_state["chips"],
            refederator=fed.Refederator(None),
            checkpoint_root=root,
            optimizer=train_lib.make_optimizer(lr=1e-2),
            step_factory=fed._default_step_factory)

    first = harness().start()
    tokens = np.asarray(train_lib.make_batch(
        jax.random.PRNGKey(7), 4, 16, cfg.vocab))
    for _ in range(5):
        first.train_step(tokens)
    assert int(first.state.step) == 5
    embed = np.asarray(jax.device_get(first.state.params["embed"]))
    # drain for the (never-completed) transition to generation 2 —
    # then "crash": the checkpoint is the sole surviving copy
    first._drain(2)
    assert_checkpoint_invariants(root)
    reborn = harness()
    reborn._target_generation = 2
    reborn.start(resume=True)
    assert int(reborn.state.step) == 5              # not reset
    np.testing.assert_array_equal(
        embed, np.asarray(jax.device_get(reborn.state.params["embed"])))
    assert reborn.restored_generation == 2
    # a start WITHOUT resume still inits fresh (historical contract)
    fresh = harness().start()
    assert int(fresh.state.step) == 0


# ---------------------------------------------------------------------------
# the multi-process acceptance e2es (real subprocesses, gloo/CPU)
# ---------------------------------------------------------------------------

def _member_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("TPU_EVENT_LOG", None)
    return env


def _spawn_member(base, group, i, tmp_path, *, hold_dir=None,
                  barrier_timeout=6.0):
    status = str(tmp_path / f"member-{i}.jsonl")
    argv = [sys.executable, "-m", "gpumounter_tpu.jaxcheck.federation",
            "--master", base, "--group", group,
            "--member", f"default/workload-{i}",
            "--checkpoint-root", str(tmp_path / "ckpt"),
            "--local-devices", "2", "--status-file", status,
            "--stop-file", str(tmp_path / "stop"),
            "--barrier-timeout", str(barrier_timeout),
            "--seq-len", "48"]
    if hold_dir is not None:
        argv += ["--hold-dir", str(hold_dir)]
    proc = subprocess.Popen(argv, cwd=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        env=_member_env(), stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT, start_new_session=True)
    return proc, status


def _records(status_path):
    try:
        with open(status_path) as f:
            return [json.loads(line) for line in f if line.strip()]
    except OSError:
        return []


def _wait_for(predicate, timeout_s=90.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while True:
        value = predicate()
        if value:
            return value
        assert time.monotonic() < deadline, f"timed out waiting: {what}"
        time.sleep(0.25)


def _steps_at(status_path, generation, world, n=2):
    def check():
        steps = [r for r in _records(status_path)
                 if r["phase"] == "step"
                 and r["generation"] == generation
                 and r["world_devices"] == world]
        return steps if len(steps) >= n else None
    return check


def _reap(procs, timeout_s=30.0):
    for proc in procs:
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=5)


def test_multiprocess_resize_2_4_2_end_to_end(tmp_path):
    """THE acceptance flow: two real member processes federate over
    gloo (2 virtual CPU devices each), train, and ride /slice/resize
    2→4→2 hosts through the full drain → barrier → re-initialize →
    restore-resharded protocol — step counter and loss trajectory
    intact across BOTH transitions, members resized out exit clean."""
    stack = _stack(tmp_path, hosts=4)
    procs = []
    try:
        status, body = _post(f"{stack.base}/addtpuslice", _target(2))
        assert status == 200, body
        group = body["group"]
        p0, s0 = _spawn_member(stack.base, group, 0, tmp_path)
        p1, s1 = _spawn_member(stack.base, group, 1, tmp_path)
        procs = [p0, p1]
        # generation 1: a 2-process / 4-device world training
        _wait_for(_steps_at(s0, 1, 4, n=3), what="gen-1 steps")
        # GROW 2 → 4 hosts: barrier gen 2 expects all four members
        status, body = _post(f"{stack.base}/slice/resize", _target(4))
        assert status == 200, body
        assert body["generation"] == 2
        p2, s2 = _spawn_member(stack.base, group, 2, tmp_path)
        p3, s3 = _spawn_member(stack.base, group, 3, tmp_path)
        procs += [p2, p3]
        _wait_for(_steps_at(s0, 2, 8, n=3), what="gen-2 steps")
        _wait_for(_steps_at(s2, 2, 8, n=1), what="member-2 joined")
        # SHRINK 4 → 2: members 2/3 are refused at the barrier and exit
        status, body = _post(f"{stack.base}/slice/resize", _target(2))
        assert status == 200, body
        assert body["generation"] == 3
        _wait_for(_steps_at(s0, 3, 4, n=3), what="gen-3 steps")
        for proc in (p2, p3):
            assert proc.wait(timeout=60) == 0
        assert any(r["phase"] == "resized_out" for r in _records(s2))
        with open(tmp_path / "stop", "w") as f:
            f.write("1")
        _reap([p0, p1])
        assert p0.returncode == 0 and p1.returncode == 0

        records = _records(s0)
        steps = [r for r in records if r["phase"] == "step"]
        # the step counter NEVER resets: strictly increasing across
        # both reshapes, and the world really was 4 → 8 → 4 devices
        numbers = [r["step"] for r in steps]
        assert numbers == sorted(set(numbers)), numbers
        worlds = [r["world_devices"] for r in steps]
        assert {1: 4, 2: 8, 3: 4} == {
            r["generation"]: r["world_devices"] for r in steps}
        reshapes = [r for r in records if r["phase"] == "reshape_done"]
        assert [r["generation"] for r in reshapes] == [2, 3]
        assert all(r["rolled_back"] is False for r in reshapes)
        # parameters survived both transitions bit-for-bit: the
        # fingerprint before each drain equals the one after restore
        begins = [r for r in records if r["phase"] == "reshape_begin"]
        for begin, done in zip(begins, reshapes):
            assert begin["fingerprint"] == pytest.approx(
                done["fingerprint"], rel=1e-4)
        # the loss trajectory descends across the whole ride
        losses = [r["loss"] for r in steps]
        assert len(losses) >= 9
        assert (sum(losses[-3:]) / 3) < (sum(losses[:3]) / 3), losses
        # both signals agree with ground truth everywhere
        assert_slice_invariants(stack.gateway.broker,
                                [r.sim for r in stack.rigs],
                                kube=stack.master_kube)
        assert_checkpoint_invariants(str(tmp_path / "ckpt"))
    finally:
        _reap(procs, timeout_s=5.0)
        stack.close()


def test_member_sigkill_mid_resize_rolls_back_and_reforms(tmp_path):
    """Fault injection: the COORDINATOR member is SIGKILLed in the
    mid-resize window (drained, torn down, not yet joined). The gen-2
    barrier sticks at joined < expected (doctor-visible), survivors
    park; the operator moves the generation past the dead member —
    exactly what slice self-healing does on a node death — and the
    survivors re-form under a re-elected coordinator, restoring the
    last-good checkpoint: step counter and trajectory intact."""
    stack = _stack(
        tmp_path, hosts=4,
        broker_config=BrokerConfig(resize_barrier_timeout_s=1.0))
    hold = tmp_path / "hold"
    hold.mkdir()
    procs = []
    try:
        status, body = _post(f"{stack.base}/addtpuslice", _target(2))
        assert status == 200, body
        group = body["group"]
        p0, s0 = _spawn_member(stack.base, group, 0, tmp_path,
                               hold_dir=hold, barrier_timeout=3.0)
        p1, s1 = _spawn_member(stack.base, group, 1, tmp_path,
                               hold_dir=hold, barrier_timeout=3.0)
        procs = [p0, p1]
        # release the initial (generation 1) federation hold
        _wait_for(lambda: os.path.exists(
            hold / "default--workload-0.ready-1") and os.path.exists(
            hold / "default--workload-1.ready-1"), what="gen-1 holds")
        (hold / "go-1").touch()
        _wait_for(_steps_at(s0, 1, 4, n=3), what="gen-1 steps")

        # GROW 2 → 4: members drain gen 2, tear down, and HOLD at the
        # pre-join seam — the deterministic mid-resize window
        status, body = _post(f"{stack.base}/slice/resize", _target(4))
        assert status == 200, body
        assert body["generation"] == 2
        _wait_for(lambda: os.path.exists(
            hold / "default--workload-0.ready-2") and os.path.exists(
            hold / "default--workload-1.ready-2"), what="gen-2 holds")
        # SIGKILL member 0 — the jax coordinator — inside the window
        os.killpg(p0.pid, signal.SIGKILL)
        p0.wait(timeout=10)
        (hold / "go-2").touch()
        # the two NEW members join normally (no hold)
        p2, s2 = _spawn_member(stack.base, group, 2, tmp_path,
                               barrier_timeout=3.0)
        p3, s3 = _spawn_member(stack.base, group, 3, tmp_path,
                               barrier_timeout=3.0)
        procs += [p2, p3]
        # barrier sticks at 3/4 — missing exactly the killed member —
        # and the master surfaces it (doctor WARN path pinned in the
        # unit above; here the raw surface)
        def stuck():
            _, barrier = _get(
                f"{stack.base}/slice/barrier?group={group}")
            return barrier if (barrier.get("generation") == 2
                               and len(barrier.get("joined") or [])
                               == 3 and barrier.get("stuck")) else None
        barrier = _wait_for(stuck, what="stuck 3/4 barrier")
        assert barrier["missing"] == ["default/workload-0"]
        # no survivor restored: nobody is stepping at generation 2
        assert not [r for r in _records(s1) if r["phase"] == "step"
                    and r["generation"] == 2]

        # the control plane moves past the dead member (the operator's
        # resize here; repair_group drives this same bump on a node
        # death) — barrier gen 3 for the three live members, coordinator
        # re-elected to member 1
        status, body = _post(f"{stack.base}/slice/resize",
                             _target(3, members=[1, 2, 3]))
        assert status == 200, body
        assert body["generation"] == 3
        _wait_for(lambda: os.path.exists(
            hold / "default--workload-1.ready-3"), what="gen-3 hold")
        (hold / "go-3").touch()
        # survivors re-form a 3-process / 6-device world and keep
        # training — restored from the LAST-GOOD checkpoint
        steps = _wait_for(_steps_at(s1, 3, 6, n=3),
                          what="gen-3 steps")
        records = _records(s1)
        done = [r for r in records if r["phase"] == "reshape_done"]
        assert done and done[-1]["generation"] == 3
        assert done[-1]["restored_generation"] == 2
        # the drained state at the moment of transition IS what came
        # back: fingerprint preserved through kill + rollback
        begin = [r for r in records if r["phase"] == "reshape_begin"][-1]
        assert done[-1]["fingerprint"] == pytest.approx(
            begin["fingerprint"], rel=1e-4)
        # step counter intact (the steps taken at gen 1 are not lost)
        gen1_last = max(r["step"] for r in records
                        if r["phase"] == "step"
                        and r["generation"] == 1)
        assert steps[0]["step"] == gen1_last + 1
        losses = [r["loss"] for r in records if r["phase"] == "step"]
        assert (sum(losses[-3:]) / 3) < (sum(losses[:3]) / 3), losses
        with open(tmp_path / "stop", "w") as f:
            f.write("1")
        _reap([p1, p2, p3])
        assert p1.returncode == 0
        assert p2.returncode == 0 and p3.returncode == 0
        assert_slice_invariants(stack.gateway.broker,
                                [r.sim for r in stack.rigs],
                                kube=stack.master_kube)
        assert_checkpoint_invariants(str(tmp_path / "ckpt"))
    finally:
        _reap(procs, timeout_s=5.0)
        stack.close()
