"""Partial-host visibility contract (SURVEY.md §7 acceptance:
TPU_VISIBLE_CHIPS / libtpu re-enumeration).

After a SINGLE-mount of 1 of a 4-chip host's chips, the pod's /dev holds
only the mounted chip's node (the mounter creates nodes per attached chip).
libtpu would probe the absent siblings at init; the probe pins
TPU_VISIBLE_CHIPS to exactly the present nodes first. Whole-host attaches
need no pin, operator-set values win, and the pin is re-derived between
wait_for_devices polls so widening attaches widen the pin.
"""

import os

from gpumounter_tpu.jaxcheck.probe import (configure_visible_chips,
                                           visible_chip_indices)


def test_indices_from_present_nodes(tmp_path):
    (tmp_path / "accel2").touch()
    (tmp_path / "accel0").touch()
    (tmp_path / "vfio").mkdir()          # companions don't count as chips
    (tmp_path / "accelerator-weird").touch()
    assert visible_chip_indices(str(tmp_path)) == [0, 2]


def test_no_nodes_means_none(tmp_path):
    assert visible_chip_indices(str(tmp_path)) is None


def test_configure_sets_env_from_nodes(tmp_path):
    (tmp_path / "accel1").touch()
    env = {}
    assert configure_visible_chips(str(tmp_path), env) == "1"
    assert env["TPU_VISIBLE_CHIPS"] == "1"


def test_configure_respects_operator_pin(tmp_path):
    (tmp_path / "accel1").touch()
    env = {"TPU_VISIBLE_CHIPS": "0,1,2,3"}
    assert configure_visible_chips(str(tmp_path), env) == "0,1,2,3"
    assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"


def test_configure_noop_without_nodes(tmp_path):
    env = {}
    assert configure_visible_chips(str(tmp_path), env) is None
    assert "TPU_VISIBLE_CHIPS" not in env


def test_whole_host_pin_lists_all_chips(tmp_path):
    for i in range(4):
        (tmp_path / f"accel{i}").touch()
    env = {}
    assert configure_visible_chips(str(tmp_path), env) == "0,1,2,3"


def test_wait_for_devices_widens_pin_between_polls(tmp_path, monkeypatch):
    """FAQ promise: a widening attach widens the pin. The probe auto-pins
    before the first backend init; between polls it must re-derive from
    the (now larger) device-node set — even though its OWN earlier pin is
    sitting in the environment (the round-5 review bug: the auto pin was
    mistaken for an operator pin and frozen)."""
    from gpumounter_tpu.jaxcheck import probe

    monkeypatch.delenv("TPU_VISIBLE_CHIPS", raising=False)
    (tmp_path / "accel0").touch()

    counts = iter([1, 1, 8])        # below `expected` until the 3rd poll
    monkeypatch.setattr(probe, "device_summary",
                        lambda: {"device_count": next(counts)})
    reinits = []

    def fake_reinit():
        # the hot-attach lands while the probe is polling
        (tmp_path / "accel1").touch()
        reinits.append(os.environ.get("TPU_VISIBLE_CHIPS"))

    monkeypatch.setattr(probe, "reinitialize_backend", fake_reinit)
    probe.configure_visible_chips(str(tmp_path))     # run_probe's first pin
    assert os.environ["TPU_VISIBLE_CHIPS"] == "0"
    probe.wait_for_devices(8, timeout_s=10, poll_s=0.01,
                           dev_root=str(tmp_path), auto_visible=True)
    # the pin was DROPPED before each backend re-init and re-derived after
    assert reinits == [None, None]
    assert os.environ["TPU_VISIBLE_CHIPS"] == "0,1"
    monkeypatch.delenv("TPU_VISIBLE_CHIPS", raising=False)


def test_probe_reports_visible_chips(tmp_path, monkeypatch):
    """run_probe surfaces the pin it applied (single-mount scenario: the
    probe report is the operator's evidence of what libtpu was allowed to
    see)."""
    from gpumounter_tpu.jaxcheck.probe import run_probe
    (tmp_path / "accel3").touch()
    monkeypatch.delenv("TPU_VISIBLE_CHIPS", raising=False)
    report = run_probe(dev_root=str(tmp_path))
    assert report["tpu_visible_chips"] == "3"
    assert os.environ.get("TPU_VISIBLE_CHIPS") == "3"
    monkeypatch.delenv("TPU_VISIBLE_CHIPS", raising=False)
