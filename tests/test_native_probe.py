"""Native libtpuprobe.so tests — same scenarios as the Python enumerator so
the two implementations are pinned to identical behavior."""

import os
import subprocess

import pytest

from gpumounter_tpu.device.enumerator import PyEnumerator
from gpumounter_tpu.device.native_enumerator import (NativeEnumerator,
                                                     best_enumerator,
                                                     load_library)

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "gpumounter_tpu", "native")


@pytest.fixture(scope="session", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE_DIR], check=True,
                   capture_output=True)


def _mk_fake_accel(dev_root, n, major=120):
    for i in range(n):
        path = os.path.join(dev_root, f"accel{i}")
        open(path, "w").close()
        with open(path + ".majmin", "w") as f:
            f.write(f"{major}:{i}")


def test_library_loads():
    assert load_library() is not None


def test_native_enumerate_matches_python(fake_host):
    _mk_fake_accel(fake_host.dev_root, 4)
    native = NativeEnumerator(fake_host, allow_fake=True).enumerate()
    py = PyEnumerator(fake_host, allow_fake=True).enumerate()
    assert [(c.index, c.major, c.minor, c.device_path) for c in native] == \
           [(c.index, c.major, c.minor, c.device_path) for c in py]
    assert len(native) == 4


def test_native_ignores_fake_without_flag(fake_host):
    _mk_fake_accel(fake_host.dev_root, 2)
    assert NativeEnumerator(fake_host, allow_fake=False).enumerate() == []


def test_native_vfio_fallback(fake_host):
    vfio = os.path.join(fake_host.dev_root, "vfio")
    os.mkdir(vfio)
    for name in ("0", "1", "vfio"):
        open(os.path.join(vfio, name), "w").close()
    chips = NativeEnumerator(fake_host, allow_fake=True).enumerate()
    assert len(chips) == 2
    assert chips[0].device_path.endswith("/vfio/0")
    assert chips[0].companions and \
        chips[0].companions[0].host_path.endswith("/vfio/vfio")


def test_native_pci_address(fake_host):
    accel_cls = os.path.join(fake_host.sys_root, "class", "accel", "accel0")
    os.makedirs(accel_cls)
    pci_dir = os.path.join(fake_host.sys_root, "devices", "pci0",
                           "0000:07:00.0")
    os.makedirs(pci_dir)
    os.symlink(pci_dir, os.path.join(accel_cls, "device"))
    _mk_fake_accel(fake_host.dev_root, 1)
    chips = NativeEnumerator(fake_host, allow_fake=True).enumerate()
    assert chips[0].pci_address == "0000:07:00.0"


def test_native_driver_major(fake_host):
    with open(os.path.join(fake_host.proc_root, "devices"), "w") as f:
        f.write("Character devices:\n120 accel\n\nBlock devices:\n")
    enum = NativeEnumerator(fake_host, allow_fake=True)
    assert enum.driver_major("accel") == 120
    assert enum.driver_major("nosuch") is None


def test_native_busy_detection(fake_host):
    dev = os.path.join(fake_host.dev_root, "accel0")
    open(dev, "w").close()
    fd_dir = os.path.join(fake_host.proc_root, "100", "fd")
    os.makedirs(fd_dir)
    os.symlink(dev, os.path.join(fd_dir, "7"))
    os.makedirs(os.path.join(fake_host.proc_root, "200", "fd"))
    enum = NativeEnumerator(fake_host, allow_fake=True)
    assert enum.device_open_pids([100, 200, 300], [dev]) == [100]
    assert enum.device_open_pids([], [dev]) == []


def test_best_enumerator_prefers_native(fake_host):
    assert isinstance(best_enumerator(fake_host), NativeEnumerator)
