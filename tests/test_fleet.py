"""Fleet aggregator (master/fleet.py): merged cluster view over every
worker's health port, per-worker scrape breakers, and the acceptance
contract — a killed worker degrades to ``stale`` within ONE tick while
healthy nodes keep getting scraped."""

import json
import time
import urllib.request

import pytest

from gpumounter_tpu.master.fleet import FleetAggregator
from gpumounter_tpu.testing.sim import MultiNodeStack
from gpumounter_tpu.utils.events import EVENTS
from gpumounter_tpu.worker.main import start_health_server


@pytest.fixture
def two_workers():
    servers = [start_health_server(0, ready=True) for _ in range(2)]
    bases = {f"node-{i}": f"http://127.0.0.1:{s.server_port}"
             for i, s in enumerate(servers)}
    yield servers, bases
    for server in servers:
        try:
            server.shutdown()
        except Exception:   # noqa: BLE001 — one is dead mid-test
            pass


def test_tick_scrapes_every_worker_fresh(two_workers):
    _, bases = two_workers
    fleet = FleetAggregator(lambda: bases, usage_fn=lambda: {"teamA": 4},
                            scrape_timeout_s=2.0)
    states = fleet.tick()
    assert states == {"node-0": "fresh", "node-1": "fresh"}
    snap = fleet.snapshot()
    assert snap["ticks"] == 1
    assert snap["tenants"] == {"teamA": 4}
    for node in ("node-0", "node-1"):
        record = snap["nodes"][node]
        assert record["state"] == "fresh"
        assert record["missed_ticks"] == 0
        assert record["last_scrape_age_s"] is not None
        assert record["events_seq"] >= 0


def test_killed_worker_goes_stale_within_one_tick_without_stalling(
        two_workers):
    servers, bases = two_workers
    fleet = FleetAggregator(lambda: bases, scrape_timeout_s=2.0)
    assert set(fleet.tick().values()) == {"fresh"}
    servers[0].shutdown()
    t0 = time.monotonic()
    states = fleet.tick()
    elapsed = time.monotonic() - t0
    # ONE tick: the dead node is already stale, the healthy one fresh,
    # and the dead scrape (connection refused) did not stall the pass
    assert states["node-0"] == "stale"
    assert states["node-1"] == "fresh"
    assert elapsed < fleet.scrape_timeout_s + 2.0
    record = fleet.snapshot()["nodes"]["node-0"]
    assert record["missed_ticks"] == 1 and record["error"]
    # further ticks keep aging the dead node, never the healthy one
    fleet.tick()
    snap = fleet.snapshot()
    assert snap["nodes"]["node-0"]["missed_ticks"] == 2
    assert snap["nodes"]["node-1"]["missed_ticks"] == 0


def test_scrape_breaker_skips_dead_node_instead_of_redialling(
        two_workers):
    servers, bases = two_workers
    fleet = FleetAggregator(lambda: bases, scrape_timeout_s=1.0)
    servers[1].shutdown()
    for _ in range(4):          # threshold is 3: the 4th tick fails fast
        fleet.tick()
    breaker = fleet._breakers["node-1"]
    assert breaker.state == breaker.OPEN
    record = fleet.snapshot()["nodes"]["node-1"]
    assert record["state"] == "stale"
    assert "breaker open" in record["error"]
    # the healthy node is unaffected by its neighbour's open breaker
    assert fleet.snapshot()["nodes"]["node-0"]["state"] == "fresh"


def test_event_tail_is_cursor_incremental_and_node_stamped(two_workers):
    _, bases = two_workers
    only_node0 = {"node-0": bases["node-0"]}
    fleet = FleetAggregator(lambda: only_node0, scrape_timeout_s=2.0)
    EVENTS.emit("fleet_test_marker", rid="fleet-rid-1")
    fleet.tick()
    tail = list(fleet._tail)
    hits = [e for e in tail if e["kind"] == "fleet_test_marker"]
    assert hits and hits[-1]["node"] == "node-0"
    # the cursor advanced: a second tick does not re-ingest the event
    before = len(fleet._tail)
    fleet.tick()
    tail = list(fleet._tail)
    assert len([e for e in tail if e["kind"] == "fleet_test_marker"]) \
        == len(hits)
    assert len(tail) - before <= 2      # at most new events, no replays
    merged = fleet.snapshot()["events"]
    assert any(e["kind"] == "fleet_test_marker" for e in merged)


def test_worker_restart_seq_reset_rebaselines_the_cursor():
    """A restarted worker's event seq starts over at 1; the aggregator
    must detect seq moving backwards and re-baseline instead of polling
    a cursor the new process will never reach (which would silently drop
    every post-restart event forever)."""
    from gpumounter_tpu.utils.events import EventLog
    log1 = EventLog(ring_size=64)
    for _ in range(20):
        log1.emit("before_restart")
    server = start_health_server(0, ready=True, events=log1)
    bases = {"node-0": f"http://127.0.0.1:{server.server_port}"}
    fleet = FleetAggregator(lambda: bases, scrape_timeout_s=2.0)
    try:
        fleet.tick()
        record = fleet._nodes["node-0"]
        assert record.events_seq == 20
        # "restart": a fresh ring starting at seq 1
        log2 = EventLog(ring_size=64)
        log2.emit("after_restart")
        server.RequestHandlerClass.events = log2
        fleet.tick()
        assert record.events_seq == 1
        assert any(e["kind"] == "after_restart" for e in fleet._tail)
    finally:
        server.shutdown()


def test_worker_restart_past_the_cursor_rebaselines_via_boot_id():
    """A restarted worker whose NEW incarnation already emitted past the
    master's cursor (e.g. a busy boot journal replay) never moves seq
    backwards — only the payload's boot id reveals the restart. The
    aggregator must re-baseline and ingest the new stream from seq 1
    instead of silently skipping its first <cursor> events."""
    from gpumounter_tpu.utils.events import EventLog
    log1 = EventLog(ring_size=64)
    for _ in range(20):
        log1.emit("before_restart")
    server = start_health_server(0, ready=True, events=log1)
    bases = {"node-0": f"http://127.0.0.1:{server.server_port}"}
    fleet = FleetAggregator(lambda: bases, scrape_timeout_s=2.0)
    try:
        fleet.tick()
        record = fleet._nodes["node-0"]
        assert record.events_seq == 20
        assert record.events_boot == log1.boot
        # "restart": a fresh ring that is ALREADY past the cursor
        log2 = EventLog(ring_size=64)
        for _ in range(30):
            log2.emit("after_restart")
        server.RequestHandlerClass.events = log2
        fleet.tick()
        assert record.events_boot == log2.boot
        assert record.events_seq == 30
        # every post-restart event made the merged tail, including the
        # 20 the stale cursor would have skipped
        replayed = [e for e in fleet._tail
                    if e["kind"] == "after_restart"]
        assert [e["seq"] for e in replayed] == list(range(1, 31))
    finally:
        server.shutdown()


def test_vanished_worker_is_kept_visible_as_stale(two_workers):
    _, bases = two_workers
    targets = dict(bases)
    fleet = FleetAggregator(lambda: targets, scrape_timeout_s=1.0)
    fleet.tick()
    del targets["node-1"]       # directory no longer lists it
    fleet.tick()
    snap = fleet.snapshot()
    # still shown (the operator must SEE the dead node), marked stale
    assert "node-1" in snap["nodes"]


# -- acceptance: /fleetz over a live 2-worker sim stack ------------------------

def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_fleetz_live_two_workers_one_killed_mid_run(fake_host, tmp_path):
    """ISSUE 7 acceptance: /fleetz on the master shows per-node health +
    per-tenant usage aggregated from >= 2 live workers, with one worker
    killed mid-run marked stale and the rest still fresh."""
    hosts = []
    for i in range(2):
        root = tmp_path / f"host-{i}"
        for sub in ("dev", "proc", "sys/fs/cgroup"):
            (root / sub).mkdir(parents=True)
        from gpumounter_tpu.utils.config import HostPaths
        hosts.append(HostPaths(
            dev_root=str(root / "dev"), proc_root=str(root / "proc"),
            sys_root=str(root / "sys"),
            cgroup_root=str(root / "sys/fs/cgroup"),
            kubelet_socket=str(root / "pr" / "kubelet.sock")))
    stack = MultiNodeStack(hosts, n_chips=4, health=True)
    try:
        # one live attach per node so the broker holds per-tenant usage
        for i in range(2):
            payload = _get_json(
                f"{stack.base}/addtpu/namespace/default/pod/workload-{i}"
                f"/tpu/2/isEntireMount/true")
            assert payload["result"] == "SUCCESS", payload
        states = stack.gateway.fleet.tick()
        assert states == {"node-0": "fresh", "node-1": "fresh"}
        fleetz = _get_json(f"{stack.base}/fleetz")
        assert set(fleetz["nodes"]) == {"node-0", "node-1"}
        assert all(n["state"] == "fresh"
                   for n in fleetz["nodes"].values())
        # per-tenant chips in use, aggregated by the broker's lease table
        assert fleetz["tenants"].get("default") == 4
        # the merged event tail carries the attaches
        assert any(e["kind"] == "attach" for e in fleetz["events"])
        # SLO section present (engine ticked by the fleet pass)
        assert "slo" in fleetz

        # kill worker 0's health port mid-run: ONE tick marks it stale,
        # node-1 stays fresh, and the scrape pass didn't wedge
        stack.health_servers[0].shutdown()
        states = stack.gateway.fleet.tick()
        assert states["node-0"] == "stale"
        assert states["node-1"] == "fresh"
        fleetz = _get_json(f"{stack.base}/fleetz")
        assert fleetz["nodes"]["node-0"]["state"] == "stale"
        assert fleetz["nodes"]["node-1"]["state"] == "fresh"

        # tpumounterctl fleet renders the view and exits non-zero on a
        # stale node
        from gpumounter_tpu import cli
        import contextlib
        import io
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = cli.main(["--master", stack.base, "fleet"])
        rendered = out.getvalue()
        assert rc == cli.EXIT_OTHER
        assert "node-0: STALE" in rendered
        assert "node-1: FRESH" in rendered
        assert "tenants: default=4 chip(s)" in rendered
    finally:
        stack.close()


def test_fresh_cursor_does_not_count_history_as_dropped(two_workers):
    """A master joining late (since=0) against a worker whose ring has
    rotated must not report the pre-ring history as events_dropped —
    nothing was lost, the master just wasn't there."""
    for i in range(600):                # > ring size 512: forces rotation
        EVENTS.emit("test_filler", rid=f"fill-{i}")
    _, bases = two_workers
    fleet = FleetAggregator(lambda: bases, scrape_timeout_s=2.0)
    assert set(fleet.tick().values()) == {"fresh"}
    for record in fleet.snapshot()["nodes"].values():
        assert "events_dropped" not in record, record
