"""Group-committed intent-store writes (master/store.py coalescer):
queued per-record mutations fuse into ONE fenced CAS per shard within a
bounded delay — GPUOS-style operation fusion — while every durability
rule PR 8 established keeps holding: last-writer-wins per key across
the pending/dirty pair, decayed-leadership refusal (no unfenced write,
ever), deposed-leader demotion, apiserver-outage degradation to the
dirty queue, and the TPU_STORE_GROUP_COMMIT=0 off-path byte-for-byte
per-record CAS."""

import time

import pytest

from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.master.shardring import HAConfig, ShardRing
from gpumounter_tpu.master.store import IntentStore
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import Settings
from gpumounter_tpu.utils.errors import K8sApiError

from tests.test_store import NS, lease_record, waiter_record


def make_store(kube=None, shards=1, election=None, delay=60.0,
               max_keys=consts.STORE_GROUP_COMMIT_MAX_KEYS):
    """delay=60 parks the coalescer thread out of the way so tests
    drive flush_pending() deterministically; the timing test builds its
    own short-delay store."""
    kube = kube or FakeKubeClient()
    return kube, IntentStore(kube, ShardRing(shards), NS,
                             election=election,
                             group_commit_delay_s=delay,
                             group_commit_max_keys=max_keys)


def test_coalesced_mutations_land_as_one_cas_per_shard():
    kube, store = make_store()
    try:
        before = kube.cm_calls
        store.put_lease(lease_record())
        store.put_lease(lease_record(pod="workload-2"))
        store.put_waiter(waiter_record())
        store.put_waiter(waiter_record(rid="w-rid-2", pod="c2"))
        assert kube.cm_calls == before          # nothing touched yet
        landed = store.flush_pending()
        assert landed == 4
        # one CAS: the create round-trip (no prior GET — the map did
        # not exist, observe answers from the 404 path, then ONE POST)
        assert kube.cm_calls - before <= 2
        leases, waiters, torn = store.rehydrate(0)
        assert torn == 0
        assert sorted(le.pod for le in leases) == \
            ["workload", "workload-2"]
        assert sorted(w.rid for w in waiters) == ["w-rid-1", "w-rid-2"]
        # byte-identical round trip, exactly the per-record guarantee
        assert [le for le in leases if le.pod == "workload"][0] == \
            lease_record()
    finally:
        store.stop()


def test_last_writer_wins_per_key_within_a_batch():
    kube, store = make_store()
    try:
        record = waiter_record()
        store.put_waiter(record)
        store.delete_waiter(record.namespace, record.rid)
        store.put_lease(lease_record())
        store.flush_pending()
        leases, waiters, _ = store.rehydrate(0)
        assert waiters == []      # the delete superseded the put
        assert len(leases) == 1
    finally:
        store.stop()


def test_bounded_delay_flushes_without_being_driven():
    kube, store = make_store(delay=0.02)
    try:
        store.put_lease(lease_record())
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            try:
                leases, _, _ = store.rehydrate(0)
            except K8sApiError:
                leases = []
            if leases:
                break
            time.sleep(0.005)
        assert leases, "coalescer never flushed within the bounded delay"
    finally:
        store.stop()


def test_size_threshold_flushes_before_the_delay():
    kube, store = make_store(delay=30.0, max_keys=3)
    try:
        for i in range(3):
            store.put_lease(lease_record(pod=f"w{i}"))
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            try:
                if len(store.rehydrate(0)[0]) == 3:
                    break
            except K8sApiError:
                pass
            time.sleep(0.005)
        assert len(store.rehydrate(0)[0]) == 3, \
            "size threshold did not trigger an early flush"
    finally:
        store.stop()


def test_off_path_is_the_per_record_cas_byte_for_byte():
    """TPU_STORE_GROUP_COMMIT=0 (delay 0): every mutation is its own
    synchronous CAS, no coalescer thread, no pending state, and the
    snapshot payload carries no group_commit key — PR 8 exactly."""
    kube = FakeKubeClient()
    store = IntentStore(kube, ShardRing(1), NS)       # defaults: off
    assert store._flusher is None
    before = kube.cm_calls
    store.put_waiter(waiter_record())
    assert kube.cm_calls > before                     # landed inline
    assert store._pending == {}
    assert "group_commit" not in store.snapshot()
    _, waiters, _ = store.rehydrate(0)
    assert len(waiters) == 1


def test_apiserver_outage_parks_batch_dirty_and_replay_converges():
    """The crash half of the acceptance: the coalescer dies mid-flush
    (every patch/create bounces off a dead apiserver) → the whole batch
    parks in the dirty queue, lag shows, and the broker-tick replay
    (flush_dirty) lands the records byte-identically once the apiserver
    heals. No torn records either way — each CAS is one atomic
    annotation merge."""
    kube, store = make_store()
    try:
        real_create = kube.create_config_map
        real_patch = kube.patch_config_map

        def down(*a, **k):
            raise K8sApiError(503, "apiserver down", cause="refused")

        kube.create_config_map = down
        kube.patch_config_map = down
        store.put_lease(lease_record())
        store.put_waiter(waiter_record())
        assert store.flush_pending() == 0
        assert store.snapshot()["dirty"] == 2
        assert store.lag_s() > 0
        # still down: the dirty replay defers, nothing is lost
        assert store.flush_dirty() == 0
        kube.create_config_map = real_create
        kube.patch_config_map = real_patch
        assert store.flush_dirty() == 2
        leases, waiters, torn = store.rehydrate(0)
        assert torn == 0
        assert leases == [lease_record()]
        assert [w.rid for w in waiters] == ["w-rid-1"]
        assert store.snapshot()["dirty"] == 0
    finally:
        store.stop()


def test_pending_supersedes_dirty_for_the_same_key():
    """Last-writer-wins ACROSS the two queues: a key parked dirty by an
    outage must not replay over the newer value queued in the
    coalescer — enqueueing purges the stale dirty entry."""
    kube, store = make_store()
    try:
        def down(*a, **k):
            raise K8sApiError(503, "down", cause="refused")
        real_create = kube.create_config_map
        kube.create_config_map = down
        kube.patch_config_map = down
        store.put_lease(lease_record(chips=1, uuids=["0"]))
        store.flush_pending()                  # parks the stale value
        assert store.snapshot()["dirty"] == 1
        kube.create_config_map = real_create
        store.put_lease(lease_record(chips=3, uuids=["0", "2", "7"]))
        assert store.snapshot()["dirty"] == 0  # purged by the enqueue
        store.flush_pending()
        assert store.flush_dirty() == 0        # nothing stale to replay
        leases, _, _ = store.rehydrate(0)
        assert leases[0].chips == 3
    finally:
        store.stop()


class _Election:
    """Minimal election surface the store consults: enabled + token,
    plus the leaders()/replica pair flush_dirty's hand-off check reads."""

    def __init__(self, token):
        self.enabled = True
        self.replica = "m-0"
        self._token = token

    def token(self, shard):
        return self._token

    def leaders(self):
        return {}


def test_decayed_leadership_parks_instead_of_writing_unfenced():
    """The PR 8 refusal rule survives fusion: no live token → the fused
    batch must NOT land (it would be unfenced — the split-brain hole);
    it parks and the resumed leadership replays it."""
    kube = FakeKubeClient()
    election = _Election(token=None)
    store = IntentStore(kube, ShardRing(1), NS, election=election,
                        group_commit_delay_s=60.0)
    try:
        before = kube.cm_calls
        store.put_waiter(waiter_record())
        store.flush_pending()
        assert kube.cm_calls == before          # zero configmap traffic
        assert store.snapshot()["dirty"] == 1
        election._token = 3                     # leadership resumed
        assert store.flush_dirty() == 1
        _, waiters, _ = store.rehydrate(0)
        assert len(waiters) == 1
        annotations = kube.get_config_map(
            NS, store.cm_name(0))["metadata"]["annotations"]
        assert annotations[consts.STORE_FENCE_ANNOTATION] == "3"
    finally:
        store.stop()


def test_deposed_batch_parks_and_fires_on_fenced():
    """A fused batch bouncing off a HIGHER fence = this replica was
    deposed: the coalescer surfaces it through on_fenced (the broker
    demotes) instead of raising on its own thread, and the batch parks
    for the hand-off logic to discard."""
    kube = FakeKubeClient()
    election = _Election(token=2)
    store = IntentStore(kube, ShardRing(1), NS, election=election,
                        group_commit_delay_s=60.0)
    try:
        # a peer already wrote fence 7
        kube.create_config_map(NS, {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": store.cm_name(0),
                         "annotations": {
                             consts.STORE_FENCE_ANNOTATION: "7"}}})
        fences = []
        store.on_fenced = fences.append
        store.put_waiter(waiter_record())
        store.flush_pending()
        assert len(fences) == 1
        assert fences[0].shard == 0 and fences[0].fence == 7
        assert store.snapshot()["dirty"] == 1   # parked, not lost
    finally:
        store.stop()


def test_broker_tick_is_the_flush_backstop(fake_host):
    """A dead coalescer thread degrades durability to tick cadence, not
    to never: stop the flusher, mutate, and a broker tick lands the
    pending batch (flush_pending is the tick's first store step)."""
    from gpumounter_tpu.master.admission import AttachBroker, BrokerConfig
    from gpumounter_tpu.master.election import NullElection
    kube = FakeKubeClient()
    ring = ShardRing(1)
    store = IntentStore(kube, ring, NS, group_commit_delay_s=60.0)
    broker = AttachBroker(kube, BrokerConfig())
    broker.bind_ha(store, ring, None)
    store.stop()                       # the "flusher died" half
    broker.leases.record("default", "workload", "teamA", "normal",
                         ["0", "1"], node="node-a", rid="r1")
    assert store._pending               # queued, nobody to flush it
    broker.tick()
    leases, _, _ = store.rehydrate(0)
    assert [le.pod for le in leases] == ["workload"]
    assert store.on_fenced == broker._on_fenced


def test_group_commit_knob_plumbs_from_env():
    assert Settings().store_group_commit_s == 0.0
    assert Settings.from_env({}).store_group_commit_s == \
        consts.DEFAULT_STORE_GROUP_COMMIT_S
    assert Settings.from_env(
        {"TPU_STORE_GROUP_COMMIT": "0"}).store_group_commit_s == 0.0
    assert Settings.from_env(
        {"TPU_STORE_GROUP_COMMIT": "0.02"}).store_group_commit_s == 0.02
    with pytest.raises(ValueError):
        Settings.from_env({"TPU_STORE_GROUP_COMMIT": "-1"})
    assert HAConfig().group_commit_delay_s == 0.0
    ha = HAConfig.from_settings(Settings.from_env({}))
    assert ha.group_commit_delay_s == consts.DEFAULT_STORE_GROUP_COMMIT_S


def test_coalesced_stack_holds_broker_invariants_across_outage(fake_host):
    """Acceptance: a full master stack running group commit takes an
    apiserver outage mid-stream (the coalescer's flush dies), keeps
    admitting, and after the heal the dirty replay converges — cluster
    ground truth, lease table and store agree
    (assert_broker_invariants(store=))."""
    from gpumounter_tpu.master.admission import BrokerConfig
    from gpumounter_tpu.testing.chaos import assert_broker_invariants
    from gpumounter_tpu.testing.sim import MultiMasterStack, WorkerRig
    import http.client
    import json

    rig = WorkerRig(fake_host, n_chips=4, informer=False)
    stack = MultiMasterStack(rig, masters=1, shards=1,
                             broker_config=BrokerConfig(),
                             store=True, election=True,
                             group_commit_s=0.005)
    try:
        stack.wait_converged()
        base = stack.bases[0]
        host, _, port = base.rpartition("//")[2].rpartition(":")

        def req(method, path):
            conn = http.client.HTTPConnection(host, int(port), timeout=30)
            conn.request(method, path, body=b"")
            body = json.loads(conn.getresponse().read())
            conn.close()
            return body

        pod2 = rig.sim.add_target_pod(name="workload-b", uid="uid-b")
        rig.provision_container(pod2)
        assert req("GET", "/addtpu/namespace/default/pod/workload"
                   "/tpu/2/isEntireMount/false")["result"] == "SUCCESS"
        kube = stack.kube
        real_patch = kube.patch_config_map
        real_create = kube.create_config_map

        def down(*a, **k):
            raise K8sApiError(503, "apiserver down", cause="refused")

        store = stack.gateways[0].broker.store
        kube.patch_config_map = down
        kube.create_config_map = down
        # admission keeps flowing THROUGH the outage (durability
        # degrades, availability does not — the PR 8 contract)
        assert req("GET", "/addtpu/namespace/default/pod/workload-b"
                   "/tpu/2/isEntireMount/false")["result"] == "SUCCESS"
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                store.snapshot()["dirty"] == 0:
            store.flush_pending()
            time.sleep(0.01)
        assert store.snapshot()["dirty"] > 0
        kube.patch_config_map = real_patch
        kube.create_config_map = real_create
        store.flush_pending()
        stack.gateways[0].broker.tick()         # dirty replay
        assert store.snapshot()["dirty"] == 0
        assert_broker_invariants(stack.gateways[0].broker, rig.sim,
                                 store=store)
        leases, _, torn = store.rehydrate(0)
        assert torn == 0
        assert sorted(le.pod for le in leases) == \
            ["workload", "workload-b"]
    finally:
        stack.close()
