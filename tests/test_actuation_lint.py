"""Actuation lint (AST-based, à la test_informer_lint): with the resident
agent enabled, NO module on the attach hot path may fork/exec — no
``subprocess`` usage, no ``os.system``/``os.popen``/``os.fork``/
``os.exec*``. The per-attach shell-out the agent replaced must only be
reachable through the explicit fallback seam: the ``NsenterActuator``
class inside ``actuation/nsenter.py``."""

import ast
import inspect

import gpumounter_tpu.actuation.agent as agent_mod
import gpumounter_tpu.actuation.bpf as bpf_mod
import gpumounter_tpu.actuation.cgroup as cgroup_mod
import gpumounter_tpu.actuation.gate as gate_mod
import gpumounter_tpu.actuation.mount as mount_mod
import gpumounter_tpu.actuation.nsenter as nsenter_mod
import gpumounter_tpu.allocator.allocator as allocator_mod
import gpumounter_tpu.collector.collector as collector_mod
import gpumounter_tpu.collector.podresources as podresources_mod
import gpumounter_tpu.device.enumerator as enumerator_mod
import gpumounter_tpu.device.plan as plan_mod
import gpumounter_tpu.k8s.client as client_mod
import gpumounter_tpu.k8s.informer as informer_mod
import gpumounter_tpu.worker.pool as pool_mod
import gpumounter_tpu.worker.service as service_mod

# Everything an AddTPU/RemoveTPU can touch while the agent is enabled.
HOT_PATH_MODULES = (
    agent_mod, mount_mod, cgroup_mod, bpf_mod, gate_mod,
    service_mod, pool_mod, allocator_mod,
    collector_mod, podresources_mod, enumerator_mod, plan_mod,
    client_mod, informer_mod,
)

_FORK_OS_CALLS = {"system", "popen", "fork", "forkpty", "spawnv",
                  "spawnvp", "execv", "execvp", "execve", "posix_spawn"}


def _fork_exec_offenders(tree: ast.AST, module_name: str) -> list[str]:
    offenders = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            names = [a.name for a in node.names]
            source = getattr(node, "module", None) or ""
            if "subprocess" in names or source == "subprocess":
                offenders.append(f"{module_name}: import subprocess")
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name):
            if node.value.id == "subprocess":
                offenders.append(
                    f"{module_name}: subprocess.{node.attr}")
            if node.value.id == "os" and node.attr in _FORK_OS_CALLS:
                offenders.append(f"{module_name}: os.{node.attr}")
    return offenders


def test_no_fork_exec_on_the_attach_hot_path():
    offenders = []
    for module in HOT_PATH_MODULES:
        if module is nsenter_mod:
            continue
        offenders += _fork_exec_offenders(
            ast.parse(inspect.getsource(module)), module.__name__)
    assert offenders == [], \
        f"fork/exec reachable outside the fallback seam: {offenders}"


def test_nsenter_fork_exec_confined_to_the_fallback_class():
    """Inside nsenter.py itself, every subprocess use must live in the
    NsenterActuator class — the ONE named fallback seam."""
    tree = ast.parse(inspect.getsource(nsenter_mod))
    offenders = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "NsenterActuator":
            continue
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            continue        # the module-level import itself is fine
        offenders += _fork_exec_offenders(node, "nsenter")
    assert offenders == [], \
        f"fork/exec outside NsenterActuator: {offenders}"


def test_agent_is_the_production_default():
    """The resident agent ships ON: the fork-free warm path is the
    default actuator wiring, not an opt-in."""
    from gpumounter_tpu.utils.config import Settings
    assert Settings().agent_enabled is True
    assert Settings.from_env({}).agent_enabled is True
    assert Settings.from_env({"TPU_AGENT": "0"}).agent_enabled is False


def test_mounter_single_namespace_crossing_per_container():
    """The positive half: mount/unmount actuate through ONE
    apply_device_nodes batch per container (the agent's single-crossing
    discipline), never per-node loops over create/remove."""
    for method in ("mount_chips", "unmount_chips"):
        source = inspect.getsource(getattr(mount_mod.TPUMounter, method))
        tree = ast.parse("class _T:\n" + source.replace("\n", "\n    "))
        calls = {n.attr for n in ast.walk(tree)
                 if isinstance(n, ast.Attribute)}
        assert "apply_device_nodes" in calls, method
        assert "create_device_node" not in calls, method
        assert "remove_device_node" not in calls, method
