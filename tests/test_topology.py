"""Topology-aware allocation (SURVEY.md §7 hard part 3).

Entire-mounts must form valid ICI groups on the target node's advertised GKE
TPU topology; multi-host slice attaches must target hosts that advertise ONE
slice shape. Misaligned requests get a precise 412 *before* any slave pod is
created."""

import json
import urllib.error
import urllib.request

import pytest

from gpumounter_tpu.allocator import topology
from gpumounter_tpu.testing.sim import (LiveStack, MultiNodeStack,
                                        WorkerRig, make_tpu_node)
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.errors import TopologyError
from tests.test_slice import _host, _post


# -- unit: parsing / validation rules -----------------------------------------


def test_parse_topology_product():
    assert topology.parse_topology_product("2x4") == 8
    assert topology.parse_topology_product("2x2x2") == 8
    assert topology.parse_topology_product("16x16") == 256
    assert topology.parse_topology_product("") == 0
    assert topology.parse_topology_product("bogus") == 0
    assert topology.parse_topology_product("0x4") == 0


def test_node_topology_reads_labels_and_allocatable():
    topo = topology.node_topology(make_tpu_node(
        accelerator="tpu-v5-lite-podslice", topology="2x4", chips=8))
    assert topo.accelerator == "tpu-v5-lite-podslice"
    assert topo.topology == "2x4"
    assert topo.chips_per_host == 8
    assert topo.total_chips == 8
    assert topo.num_hosts == 1 and not topo.multi_host


def test_node_topology_multi_host():
    topo = topology.node_topology(make_tpu_node(
        accelerator="tpu-v5p-slice", topology="2x2x4", chips=4))
    assert topo.total_chips == 16
    assert topo.num_hosts == 4 and topo.multi_host
    assert topology.aligned_group_sizes(topo) == [4]   # whole hosts only


def test_node_topology_none_for_unlabelled_nodes():
    assert topology.node_topology(make_tpu_node(accelerator=None)) is None
    assert topology.node_topology(None) is None


def test_aligned_group_sizes_single_host():
    topo = topology.node_topology(make_tpu_node(topology="2x4", chips=8))
    assert topology.aligned_group_sizes(topo) == [1, 2, 4, 8]
    topo4 = topology.node_topology(make_tpu_node(topology="2x2", chips=4))
    assert topology.aligned_group_sizes(topo4) == [1, 2, 4]


def test_validate_entire_mount():
    topo = topology.node_topology(make_tpu_node(topology="2x2", chips=4))
    topology.validate_entire_mount(topo, 4)          # whole host
    topology.validate_entire_mount(topo, 2)          # aligned sub-group
    topology.validate_entire_mount(None, 3)          # no topology info: free
    with pytest.raises(TopologyError) as exc:
        topology.validate_entire_mount(topo, 3)      # the VERDICT scenario
    assert "valid sizes: [1, 2, 4]" in str(exc.value)

    multi = topology.node_topology(make_tpu_node(
        accelerator="tpu-v5p-slice", topology="2x2x4", chips=4))
    with pytest.raises(TopologyError):
        topology.validate_entire_mount(multi, 2)     # sub-host on multi-host


# -- allocator/service: labelled fake nodes -----------------------------------


@pytest.fixture
def rig(tmp_path, fake_host):
    r = WorkerRig(fake_host, n_chips=4)
    yield r
    r.close()


def test_misaligned_entire_mount_rejected_before_slave_pods(rig):
    rig.sim.kube.put_node(make_tpu_node(name="node-a", topology="2x2",
                                        chips=4))
    with pytest.raises(TopologyError):
        rig.service.add_tpu("workload", "default", 3, True)
    assert rig.sim.slave_pods() == []                # nothing was created


def test_aligned_entire_mount_stamps_topology(rig):
    rig.sim.kube.put_node(make_tpu_node(name="node-a", topology="2x2",
                                        chips=4))
    outcome = rig.service.add_tpu("workload", "default", 4, True)
    assert outcome.result == consts.AddResult.SUCCESS
    for chip in outcome.chips:
        assert chip.accelerator == "tpu-v5-lite-podslice"
        assert chip.topology == "2x2"
    slaves = rig.sim.slave_pods()
    assert len(slaves) == 1
    labels = slaves[0]["metadata"]["labels"]
    assert labels[consts.CHIP_TOPOLOGY_LABEL_KEY] == "2x2"
    assert labels[consts.CHIP_ACCELERATOR_LABEL_KEY] == \
        "tpu-v5-lite-podslice"


def test_unlabelled_node_unconstrained(rig):
    rig.sim.kube.put_node(make_tpu_node(name="node-a", accelerator=None))
    outcome = rig.service.add_tpu("workload", "default", 3, True)
    assert outcome.result == consts.AddResult.SUCCESS


def test_missing_node_unconstrained(rig):
    # no put_node at all: node GET 404s, enforcement off (non-GKE clusters)
    outcome = rig.service.add_tpu("workload", "default", 3, True)
    assert outcome.result == consts.AddResult.SUCCESS


def test_single_mounts_not_topology_constrained(rig):
    rig.sim.kube.put_node(make_tpu_node(name="node-a", topology="2x2",
                                        chips=4))
    outcome = rig.service.add_tpu("workload", "default", 3, False)
    assert outcome.result == consts.AddResult.SUCCESS
    # single-chip slave pods still carry the topology stamp
    for pod in rig.sim.slave_pods():
        assert pod["metadata"]["labels"][consts.CHIP_TOPOLOGY_LABEL_KEY] \
            == "2x2"


# -- HTTP: precise 412 through the full stack ---------------------------------


def test_misaligned_mount_is_412_over_http(fake_host):
    rig = WorkerRig(fake_host, n_chips=4)
    rig.sim.kube.put_node(make_tpu_node(name="node-a", topology="2x2",
                                        chips=4))
    stack = LiveStack(rig)
    try:
        url = (f"{stack.base}/addtpu/namespace/default/pod/workload"
               "/tpu/3/isEntireMount/true")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url)
        assert exc.value.code == 412
        body = json.loads(exc.value.read())
        assert "topology-aligned" in body["message"]
        assert rig.sim.slave_pods() == []
    finally:
        stack.close()


# -- slice-level verification --------------------------------------------------


SLICE = {"pods": [{"namespace": "default", "pod": "workload-0"},
                  {"namespace": "default", "pod": "workload-1"}],
         "tpusPerHost": 4}


@pytest.fixture
def stack(tmp_path):
    s = MultiNodeStack([_host(tmp_path, 0), _host(tmp_path, 1)], n_chips=4)
    yield s
    s.close()


def test_slice_attach_mismatched_topologies_412(stack):
    stack.master_kube.put_node(make_tpu_node(
        name="node-0", accelerator="tpu-v5p-slice", topology="2x2x4",
        chips=4))
    stack.master_kube.put_node(make_tpu_node(
        name="node-1", accelerator="tpu-v5-lite-podslice", topology="2x2",
        chips=4))
    status, body = _post(f"{stack.base}/addtpuslice", SLICE)
    assert status == 412
    assert body["result"] == "TopologyMismatch"
    assert "different slice topologies" in body["message"]
    for rig in stack.rigs:
        assert rig.sim.slave_pods() == []            # nothing fanned out


def test_slice_attach_wrong_per_host_count_412(stack):
    for i in range(2):
        stack.master_kube.put_node(make_tpu_node(
            name=f"node-{i}", accelerator="tpu-v5p-slice", topology="2x2x2",
            chips=4))
    req = dict(SLICE, tpusPerHost=2)
    status, body = _post(f"{stack.base}/addtpuslice", req)
    assert status == 412
    assert "whole hosts" in body["message"]


def test_slice_attach_two_pods_one_host_412(stack):
    # move workload-1 onto node-0 in the master's view
    pod = stack.master_kube.get_pod("default", "workload-1")
    pod["spec"]["nodeName"] = "node-0"
    stack.master_kube.put_pod(pod)
    status, body = _post(f"{stack.base}/addtpuslice", SLICE)
    assert status == 412
    assert "one pod per host" in body["message"]


def test_slice_attach_matching_topologies_succeeds(stack):
    for i in range(2):
        stack.master_kube.put_node(make_tpu_node(
            name=f"node-{i}", accelerator="tpu-v5p-slice", topology="2x2x2",
            chips=4))
    status, body = _post(f"{stack.base}/addtpuslice", SLICE)
    assert status == 200 and body["result"] == "SUCCESS"
