"""Flight recorder (utils/flight.py): trigger thresholds, rate limiting,
atomic bundle writes, and the ISSUE 7 acceptance — an injected failure
burst (agent fallbacks + journal backlog through the chaos harness)
produces exactly ONE rate-limited bundle holding the correlated events,
traces, and journal tail for the failing rid."""

import json
import os

import pytest

from gpumounter_tpu.utils.errors import TPUMounterError
from gpumounter_tpu.utils.flight import (FALLBACK_BURST, FlightRecorder,
                                         RECORDER)
from gpumounter_tpu.utils.metrics import REGISTRY


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# -- unit semantics ------------------------------------------------------------

def test_disabled_recorder_notes_are_noops(tmp_path):
    rec = FlightRecorder(dir_path=None, settle_s=0.0)
    assert rec.note("journal_backlog", rid="r") is None
    assert not rec.enabled


def test_single_occurrence_triggers_dump_on_first_note(tmp_path):
    rec = FlightRecorder(str(tmp_path), min_interval_s=300.0,
                         settle_s=0.0, clock=FakeClock())
    bundle_id = rec.note("journal_backlog", rid="r1", backlog=2)
    assert bundle_id is not None
    bundle = FlightRecorder.load(str(tmp_path), bundle_id)
    assert bundle["trigger"] == "journal_backlog"
    assert bundle["rid"] == "r1"
    assert bundle["context"] == {"backlog": 2}
    # atomic write: no .tmp residue
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


def test_agent_fallbacks_need_a_burst(tmp_path):
    clock = FakeClock()
    rec = FlightRecorder(str(tmp_path), min_interval_s=0.0, settle_s=0.0, clock=clock)
    for i in range(FALLBACK_BURST - 1):
        assert rec.note("agent_fallback", reason="stopped") is None
    assert rec.note("agent_fallback", reason="stopped") is not None


def test_rate_limit_suppresses_and_counts(tmp_path):
    clock = FakeClock()
    rec = FlightRecorder(str(tmp_path), min_interval_s=300.0, settle_s=0.0, clock=clock)
    before = REGISTRY.flight_suppressed.value()
    assert rec.note("circuit_open", target="w1") is not None
    assert rec.note("journal_backlog", rid="r2") is None     # suppressed
    assert REGISTRY.flight_suppressed.value() == before + 1
    clock.t += 301.0
    assert rec.note("journal_backlog", rid="r2") is not None
    assert len(FlightRecorder.list_bundles(str(tmp_path))) == 2


def test_failed_write_releases_the_rate_limit_slot(tmp_path):
    """An unwritable flight dir must not swallow the incident: the slot
    claimed before the write is given back, so the NEXT trigger retries
    instead of counting as 'suppressed' with zero bundles on disk."""
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("")
    rec = FlightRecorder(str(blocker), min_interval_s=300.0,
                         settle_s=0.0, clock=FakeClock())
    assert rec.note("circuit_open", target="w1") is None   # write failed
    rec.dir = str(tmp_path / "flight")                     # volume fixed
    bundle_id = rec.note("journal_backlog", rid="r1")
    assert bundle_id is not None                           # NOT suppressed
    assert FlightRecorder.load(rec.dir, bundle_id)


def test_raising_provider_degrades_to_error_string(tmp_path):
    rec = FlightRecorder(str(tmp_path), min_interval_s=0.0,
                         settle_s=0.0, clock=FakeClock())
    rec.providers["broken"] = lambda: 1 / 0
    rec.providers["fine"] = lambda: {"ok": True}
    bundle_id = rec.note("circuit_open")
    bundle = FlightRecorder.load(str(tmp_path), bundle_id)
    assert bundle["fine"] == {"ok": True}
    assert "ZeroDivisionError" in bundle["broken"]["error"]


def test_list_bundles_newest_first_and_flight_cli(tmp_path):
    clock = FakeClock()
    rec = FlightRecorder(str(tmp_path), min_interval_s=0.0, settle_s=0.0, clock=clock)
    first = rec.note("circuit_open", target="a")
    second = rec.note("journal_backlog", rid="rX")
    bundles = FlightRecorder.list_bundles(str(tmp_path))
    assert [b["id"] for b in bundles] == [second, first]

    from gpumounter_tpu import cli
    import contextlib
    import io
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli.main(["flight", "list", "--dir", str(tmp_path)])
    assert rc == 0
    assert second in out.getvalue() and first in out.getvalue()
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli.main(["flight", "show", second, "--dir", str(tmp_path)])
    assert rc == 0
    assert "trigger=journal_backlog" in out.getvalue()
    assert "rid=rX" in out.getvalue()
    # unknown bundle: clean non-zero, not a traceback
    assert cli.main(["flight", "show", "nope",
                     "--dir", str(tmp_path)]) == cli.EXIT_OTHER


def test_bundle_order_is_numeric_past_the_zero_pad(tmp_path):
    """Ids zero-pad to 4 digits, so lexical order inverts at 10000 —
    pruning must still delete the OLDEST bundle and list_bundles must
    keep newest-first (a recorder that has dumped 10k bundles over its
    life would otherwise destroy fresh incident evidence)."""
    from gpumounter_tpu.utils import flight as flight_mod
    for bid in (9999, 10000):
        name = f"flight-{bid:04d}-journal_backlog.json"
        (tmp_path / name).write_text(json.dumps(
            {"id": name[:-5], "trigger": "journal_backlog", "ts": bid}))
    bundles = FlightRecorder.list_bundles(str(tmp_path))
    assert [b["id"] for b in bundles] == [
        "flight-10000-journal_backlog", "flight-9999-journal_backlog"]
    # counter resumes past the highest id on disk
    rec = FlightRecorder(str(tmp_path), min_interval_s=0.0, settle_s=0.0,
                         clock=FakeClock())
    assert rec._next_id() == 10001
    # prune keeps the numerically newest MAX_BUNDLES
    for bid in range(10001, 10001 + flight_mod.MAX_BUNDLES):
        (tmp_path / f"flight-{bid}-circuit_open.json").write_text("{}")
    rec._prune()
    kept = sorted(os.listdir(str(tmp_path)),
                  key=FlightRecorder._bundle_order)
    assert len(kept) == flight_mod.MAX_BUNDLES
    assert kept[-1] == (
        f"flight-{10000 + flight_mod.MAX_BUNDLES}-circuit_open.json")
    assert "flight-9999-journal_backlog.json" not in kept


# -- acceptance: chaos-injected failure burst → exactly one bundle -------------

def test_failure_burst_produces_exactly_one_correlated_bundle(
        fake_host, tmp_path):
    """Agent fallbacks + an interrupted rollback (journal backlog), all
    for one failing rid: ONE bundle appears (rate limit swallows the
    rest) and it correlates the rid's events, traces and journal tail."""
    from gpumounter_tpu.testing.chaos import ChaosRig, Fault, FaultPlan
    from gpumounter_tpu.utils.errors import ActuationError

    flight_dir = str(tmp_path / "flight")
    chaos = ChaosRig(fake_host, agent=True)
    rig = chaos.rig
    RECORDER.configure(flight_dir, min_interval_s=300.0, settle_s=0.25)
    RECORDER.providers["journal"] = rig.journal.snapshot
    try:
        # the resident agent is down: every actuation degrades to the
        # fallback — which itself fails on create, so the attach rolls
        # back; the rollback's slave-pod deletes hit an apiserver outage,
        # leaving the journal record revert_pending (backlog)
        rig.agent.stop()
        fallback = rig.actuator.fallback
        orig_create = fallback.create_device_node

        def failing_create(*args, **kwargs):
            raise ActuationError("injected fallback failure")

        fallback.create_device_node = failing_create
        chaos.install(FaultPlan("delete-outage", [
            Fault(op="DELETE", resource="pods", times=50, status=500)]))
        suppressed_before = REGISTRY.flight_suppressed.value()
        try:
            with pytest.raises(TPUMounterError):
                rig.service.add_tpu("workload", "default", 2, True,
                                    request_id="rid-burst")
            # keep the burst coming: a second pod's attach degrades the
            # same way (the first pod's leaked slave pod would deny on
            # mount policy before ever reaching actuation) — every
            # further trigger must be rate-limited away
            pod2 = rig.sim.add_target_pod(
                name="workload-2", uid="uid-w2",
                container_id="containerd://" + "cd" * 32)
            rig.provision_container(pod2)
            with pytest.raises(TPUMounterError):
                rig.service.add_tpu("workload-2", "default", 1, True,
                                    request_id="rid-burst-2")
        finally:
            fallback.create_device_node = orig_create

        # collection is settle-deferred so the failing request's own
        # trace lands in the bundle — wait for the write
        import time
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline \
                and not FlightRecorder.list_bundles(flight_dir):
            time.sleep(0.05)
        bundles = FlightRecorder.list_bundles(flight_dir)
        assert len(bundles) == 1, \
            f"expected exactly one rate-limited bundle, got {bundles}"
        assert REGISTRY.flight_suppressed.value() > suppressed_before
        bundle = FlightRecorder.load(flight_dir, bundles[0]["id"])
        assert bundle["rid"] == "rid-burst"

        # correlated events: the failing rid's whole lifecycle is inside
        rid_kinds = [e["kind"] for e in bundle["rid_events"]]
        assert "journal_intent" in rid_kinds
        assert "journal_revert_pending" in rid_kinds
        assert "agent_fallback" in rid_kinds
        # correlated traces: the EXCEPTION attach for this rid
        rid_traces = bundle["traces"]["rid"]
        assert any(t["result"] == "EXCEPTION" for t in rid_traces)
        # journal tail: the revert_pending record for this rid's attach
        journal = bundle["journal"]
        assert journal["backlog"] >= 1
        assert any("rid-burst" in (r.get("jid") or "")
                   for r in journal["incomplete"])
    finally:
        RECORDER.providers.pop("journal", None)
        RECORDER.configure(None)
        chaos.close()


def test_restart_does_not_overwrite_previous_incarnations_bundles(tmp_path):
    """Bundle ids seed from what's already on disk: a crash-looping
    process (fresh recorder each boot, same trigger) must ADD a bundle,
    not os.replace the previous incarnation's forensic evidence."""
    first = FlightRecorder(str(tmp_path), min_interval_s=0.0, settle_s=0.0,
                           clock=FakeClock())
    first_id = first.note("journal_backlog", rid="boot1")
    assert first_id == "flight-0001-journal_backlog"
    # process restarts: a brand-new recorder over the same TPU_FLIGHT_DIR
    reborn = FlightRecorder(str(tmp_path), min_interval_s=0.0, settle_s=0.0,
                            clock=FakeClock())
    second_id = reborn.note("journal_backlog", rid="boot2")
    assert second_id == "flight-0002-journal_backlog"
    assert FlightRecorder.load(str(tmp_path), first_id)["rid"] == "boot1"
    assert FlightRecorder.load(str(tmp_path), second_id)["rid"] == "boot2"


def test_flight_dumps_counter_is_preseeded_per_trigger():
    """increase() over a series that first appears at value 1 reads 0 —
    every trigger's series must exist at 0 before its first bundle or
    the bundle-written alert misses one-bundle incidents."""
    from gpumounter_tpu.utils.metrics import Registry
    reg = Registry()
    for trigger in ("fast_burn", "agent_fallback", "journal_backlog",
                    "circuit_open"):
        assert reg.flight_dumps.value(trigger=trigger) == 0.0
    assert "tpumounter_flight_dumps_total" in reg.render_text()
