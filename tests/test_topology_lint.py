"""Topology-plane lint (AST-based, à la test_usage_lint): fragmentation
scoring must stay OFF every request path. The fleet tick thread owns ALL
scoring; scrape threads only ingest raw /topoz payloads; request threads
(worker health port, master gateway) serve already-computed snapshots.
These lints pin that, plus the telemetry pairing and the default:

1. no hot-path module can even import ``master.topology`` or
   ``collector.topology`` (exact module names — ``allocator.topology``
   is a legitimate hot-path import and must not trip this);
2. both /topoz handlers serve ``snapshot()`` only;
3. scoring (``_compute``) is reachable from ``tick()`` alone, and the
   aggregator drives ``topology.tick`` from its own tick only;
4. a defrag candidate's counter and event fire together or not at all
   (the ``_note_candidate`` seam);
5. the plane ships ON by default (``TPU_TOPOLOGY=0`` reverts).
"""

import ast
import inspect

import gpumounter_tpu.actuation.mount as mount_mod
import gpumounter_tpu.allocator.allocator as allocator_mod
import gpumounter_tpu.collector.collector as collector_mod
import gpumounter_tpu.collector.topology as nodetopo_mod
import gpumounter_tpu.master.fleet as fleet_mod
import gpumounter_tpu.master.topology as fleettopo_mod
import gpumounter_tpu.worker.grpc_server as grpc_mod
import gpumounter_tpu.worker.service as service_mod

# Everything an AddTPU/RemoveTPU request thread executes.
HOT_PATH_MODULES = (service_mod, grpc_mod, allocator_mod, mount_mod,
                    collector_mod)
# Exact names — a substring match would flag the hot path's legitimate
# gpumounter_tpu.allocator.topology import.
FORBIDDEN_IMPORTS = {"gpumounter_tpu.master.topology",
                     "gpumounter_tpu.collector.topology"}


def _imports(tree: ast.AST) -> set[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            out |= {a.name for a in node.names}
        elif isinstance(node, ast.ImportFrom):
            out.add(node.module or "")
    return out


def _method_callers(module, attr: str) -> list[str]:
    """Names of the functions in ``module`` that call ``<x>.<attr>(...)``."""
    tree = ast.parse(inspect.getsource(module))
    callers = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == attr:
                    callers.append(node.name)
    return callers


def test_no_hot_path_module_imports_the_topology_plane():
    offenders = []
    for module in HOT_PATH_MODULES:
        tree = ast.parse(inspect.getsource(module))
        hits = _imports(tree) & FORBIDDEN_IMPORTS
        if hits:
            offenders.append(f"{module.__name__}: {sorted(hits)}")
    assert offenders == [], \
        f"topology plane reachable from the hot path: {offenders}"


def test_worker_topoz_handler_serves_snapshot_only():
    """GET /topoz answers already-assembled state: the health handler
    may call ``snapshot()`` but never enumerate, probe, or resample —
    a scrape must not become device work on the request thread."""
    import gpumounter_tpu.worker.main as main_mod
    source = inspect.getsource(main_mod._HealthHandler)
    assert ".snapshot()" in source      # the sanctioned read
    assert "update_status" not in source
    assert "sample_once" not in source


def test_master_topoz_route_serves_snapshot_only():
    """The gateway's /topoz serves FleetTopology.snapshot() — it never
    drives a tick or ingests from a request thread."""
    import gpumounter_tpu.master.gateway as gateway_mod
    source = inspect.getsource(gateway_mod)
    assert "self.topology.snapshot()" in source
    tree = ast.parse(source)
    offenders = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("tick", "ingest", "_compute") \
                and isinstance(node.func.value, ast.Attribute) \
                and node.func.value.attr == "topology":
            offenders.append(node.func.attr)
    assert offenders == [], \
        f"gateway drives topology compute from a request thread: {offenders}"


def test_snapshot_performs_no_inventory_or_label_reads():
    """The worker /topoz serving path reads the collector's CACHED
    inventory and the TTL-cached label source — no enumeration, no
    uncached apiserver GET per scrape."""
    source = inspect.getsource(nodetopo_mod.NodeTopologyView.snapshot)
    for forbidden in ("update_status", "get_node", "probe.sample",
                      "sample_once"):
        assert forbidden not in source, forbidden


def test_scoring_runs_only_from_the_tick_thread():
    """Inside master/topology.py, ``_compute`` is invoked from exactly
    one place: ``tick()``. Request threads serve its stored result."""
    callers = _method_callers(fleettopo_mod, "_compute")
    assert callers == ["tick"], \
        f"_compute called outside tick(): {callers}"


def test_aggregator_ticks_topology_from_its_own_tick_only():
    """In master/fleet.py, ``<x>.topology.tick(...)`` appears only in
    the aggregator's own ``tick`` — scrape threads ingest, they never
    score."""
    tree = ast.parse(inspect.getsource(fleet_mod))
    callers = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "tick" \
                        and isinstance(sub.func.value, ast.Attribute) \
                        and sub.func.value.attr == "topology":
                    callers.append(node.name)
    assert callers == ["tick"], \
        f"topology scored off the fleet tick thread: {callers}"


def test_defrag_candidate_metric_and_event_are_paired():
    """``defrag_candidates.inc`` and ``EVENTS.emit("defrag_candidate")``
    each have exactly one call site in master/topology.py — the
    ``_note_candidate`` seam — so the counter and the event can never
    drift apart."""
    tree = ast.parse(inspect.getsource(fleettopo_mod))
    inc_callers, emit_callers = [], []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call) \
                    or not isinstance(sub.func, ast.Attribute):
                continue
            if sub.func.attr == "inc" \
                    and isinstance(sub.func.value, ast.Attribute) \
                    and sub.func.value.attr == "defrag_candidates":
                inc_callers.append(node.name)
            if sub.func.attr == "emit" and sub.args \
                    and isinstance(sub.args[0], ast.Constant) \
                    and sub.args[0].value == "defrag_candidate":
                emit_callers.append(node.name)
    assert inc_callers == ["_note_candidate"], inc_callers
    assert emit_callers == ["_note_candidate"], emit_callers


def test_topology_is_the_production_default():
    from gpumounter_tpu.master.topology import enabled
    from gpumounter_tpu.utils.config import Settings
    assert Settings().topology_enabled is True
    assert Settings.from_env({}).topology_enabled is True
    assert Settings.from_env({"TPU_TOPOLOGY": "0"}).topology_enabled \
        is False
    assert enabled({}) is True
    assert enabled({"TPU_TOPOLOGY": "0"}) is False
