"""Shared pod informer (k8s/informer.py): one list+watch per scope serving
every hot-path read, with resourceVersion fencing and graceful fall-through.
"""

import threading
import time

import pytest

from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.k8s.informer import (PodCacheReads, PodInformer,
                                         _selector_clauses)
from gpumounter_tpu.testing.chaos import Fault, FaultInjector
from gpumounter_tpu.utils.errors import PodNotFoundError


def _pod(name, namespace="tpu-pool", labels=None, phase="Pending"):
    return {"metadata": {"name": name, "namespace": namespace,
                         "labels": labels or {}},
            "status": {"phase": phase}}


class _CountingKube(FakeKubeClient):
    def __init__(self):
        super().__init__()
        self.list_calls = 0
        self.get_calls = 0

    def list_pods_with_version(self, namespace, label_selector=None):
        self.list_calls += 1
        return super().list_pods_with_version(namespace, label_selector)

    def get_pod(self, namespace, name):
        self.get_calls += 1
        return super().get_pod(namespace, name)


@pytest.fixture
def kube():
    return _CountingKube()


@pytest.fixture
def informer(kube):
    inf = PodInformer(kube, "tpu-pool", watch_chunk_s=1.0).start()
    yield inf
    inf.stop()


def _wait(predicate, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


# -- cache basics --------------------------------------------------------------

def test_reads_served_from_cache_without_lists(kube, informer):
    kube.put_pod(_pod("s1", labels={"app": "tpu-pool"}))
    assert _wait(lambda: informer.get("s1") is not None)
    reads = PodCacheReads(kube, [informer])
    before = kube.list_calls
    for _ in range(20):
        pods = reads.list_pods("tpu-pool", "app=tpu-pool")
        assert [p["metadata"]["name"] for p in pods] == ["s1"]
    assert kube.list_calls == before       # every read from the cache

    before_get = kube.get_calls
    assert reads.get_pod("tpu-pool", "s1")["metadata"]["name"] == "s1"
    assert kube.get_calls == before_get    # GET from the cache too


def test_cache_follows_events(kube, informer):
    reads = PodCacheReads(kube, [informer])
    kube.put_pod(_pod("s1"))
    assert _wait(lambda: informer.get("s1") is not None)
    kube.set_pod_status("tpu-pool", "s1", phase="Running")
    assert _wait(
        lambda: (informer.get("s1") or {}).get("status", {}).get("phase")
        == "Running")
    kube.delete_pod("tpu-pool", "s1")
    assert _wait(lambda: informer.get("s1") is None)
    with pytest.raises(PodNotFoundError):
        reads.get_pod("tpu-pool", "s1")    # authoritative absence


def test_uncovered_scope_falls_through(kube, informer):
    """Another namespace is not this informer's scope: the read goes to
    the real client unchanged."""
    kube.put_pod(_pod("w1", namespace="default"))
    reads = PodCacheReads(kube, [informer])
    before = kube.list_calls
    assert reads.list_pods("default")
    assert kube.list_calls == before + 1


def test_selector_coverage_is_clause_subset():
    assert _selector_clauses("a=b,c=d") == {"a=b", "c=d"}
    kube = FakeKubeClient()
    scoped = PodInformer(kube, "ns", label_selector="app=x")
    wide = PodInformer(kube, "ns", label_selector=None)
    # a namespace-wide informer covers every selector; a scoped one only
    # covers selectors that carry at least its own clauses
    reads = PodCacheReads(kube, [scoped])
    scoped._seeded = True
    assert reads._covering("ns", "app=x,owner=o") is scoped
    assert reads._covering("ns", "owner=o") is None
    reads_wide = PodCacheReads(kube, [wide])
    wide._seeded = True
    assert reads_wide._covering("ns", "anything=else") is wide


# -- resourceVersion fencing ---------------------------------------------------

def test_write_fence_forces_fallthrough_when_cache_lags(kube, informer):
    """A write the stream hasn't delivered yet: covered reads wait for the
    fence and fall through to a REAL apiserver call on timeout — the cache
    can be slow, never wrong."""
    kube.put_pod(_pod("s1"))
    assert _wait(lambda: informer.get("s1") is not None)
    reads = PodCacheReads(kube, [informer], fence_timeout_s=0.05)
    # pretend we wrote something the watch never delivers
    informer.note_write(str(int(informer.resource_version) + 100))
    before = kube.list_calls
    reads.list_pods("tpu-pool")
    assert kube.list_calls == before + 1   # fell through
    before_get = kube.get_calls
    reads.get_pod("tpu-pool", "s1")
    assert kube.get_calls == before_get + 1


def test_observe_write_makes_reads_read_your_writes(kube, informer):
    """The normal case: the event stream catches up within the fence
    timeout, so the read is served from cache AND reflects the write."""
    kube.put_pod(_pod("s1"))
    assert _wait(lambda: informer.get("s1") is not None)
    reads = PodCacheReads(kube, [informer], fence_timeout_s=5.0)
    resp = kube.patch_pod("tpu-pool", "s1",
                          {"metadata": {"labels": {"owner": "o1"}}})
    reads.observe_write(resp)
    before = kube.list_calls
    pods = reads.list_pods("tpu-pool", "owner=o1")
    assert [p["metadata"]["name"] for p in pods] == ["s1"]
    assert kube.list_calls == before


def test_get_pod_min_resource_version_demand(kube, informer):
    kube.put_pod(_pod("s1"))
    assert _wait(lambda: informer.get("s1") is not None)
    reads = PodCacheReads(kube, [informer], fence_timeout_s=0.05)
    rv = informer.get("s1")["metadata"]["resourceVersion"]
    # satisfied demand: cache hit
    before = kube.get_calls
    reads.get_pod("tpu-pool", "s1", min_resource_version=rv)
    assert kube.get_calls == before
    # unsatisfiable demand: real GET
    reads.get_pod("tpu-pool", "s1",
                  min_resource_version=str(int(rv) + 50))
    assert kube.get_calls == before + 1


# -- resilience ----------------------------------------------------------------

def test_watch_death_resyncs_and_cache_recovers(kube):
    """Stream deaths beyond the client's resume budget (4 back-to-back
    within one chunk) force a re-LIST resync (counted in watch_restarts);
    the cache converges afterwards."""
    inf = PodInformer(kube, "tpu-pool", watch_chunk_s=30.0).start()
    try:
        assert _wait(inf.ready)
        kube.faults = FaultInjector(
            [Fault(op="WATCH", resource="pods", drop=True, times=8)])
        kube.put_pod(_pod("s-new"))
        assert _wait(lambda: inf.get("s-new") is not None, timeout_s=10.0)
        assert _wait(lambda: inf.watch_restarts >= 1, timeout_s=10.0)
        assert inf.status()["seeded"]
    finally:
        kube.faults = None
        inf.stop()


def test_staleness_tracks_stream_liveness(kube, informer):
    assert _wait(informer.ready)
    kube.put_pod(_pod("s1"))
    assert _wait(lambda: informer.get("s1") is not None)
    assert informer.staleness_s() < 5.0
    status = informer.status()
    assert status["pods"] == 1
    assert status["watch_restarts"] == 0
    assert status["events_seen"] >= 1


def test_wait_for_wakes_on_events(kube, informer):
    assert _wait(informer.ready)

    def make_running():
        time.sleep(0.05)
        kube.put_pod(_pod("s1", phase="Running"))
    threading.Thread(target=make_running, daemon=True).start()
    ok = informer.wait_for(
        lambda: (informer._pods.get("s1") or {}).get(
            "status", {}).get("phase") == "Running", timeout_s=5.0)
    assert ok


def test_wait_pods_fences_before_trusting_absence(kube, informer):
    """A wait whose step interprets absence (the pool's refill wait) must
    not evaluate a cache lagging this process's own creates: with the
    fence unsatisfied, wait_pods takes the legacy LIST-seeded path, which
    sees the freshly created pod."""
    assert _wait(informer.ready)
    kube.put_pod(_pod("fresh", phase="Running"))
    # cache is actually caught up, but the fence says it is not — exactly
    # the just-created-pod window
    reads = PodCacheReads(kube, [informer], fence_timeout_s=0.05)
    informer.note_write(str(int(informer.resource_version) + 100))

    seen = []

    def step(pods):
        seen.append(set(pods))
        return "fresh" in pods

    before = kube.list_calls
    assert reads.wait_pods("tpu-pool", None, step, timeout_s=2.0)
    assert kube.list_calls > before        # legacy LIST path engaged
    assert all("fresh" in s for s in seen)


def test_handle_without_informers_is_passthrough(kube):
    kube.put_pod(_pod("s1"))
    reads = PodCacheReads(kube)
    before = kube.list_calls
    assert reads.list_pods("tpu-pool")
    assert kube.list_calls == before + 1
    status = reads.status()
    assert status["enabled"] is False
    assert status["scopes"] == []


def test_cachez_status_shape(kube, informer):
    assert _wait(informer.ready)
    reads = PodCacheReads(kube, [informer])
    status = reads.status()
    assert status["enabled"] is True
    (scope,) = status["scopes"]
    assert scope["namespace"] == "tpu-pool"
    assert scope["running"] is True
    assert "staleness_s" in scope and "watch_restarts" in scope
    assert "hit_ratio" in status
