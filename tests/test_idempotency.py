"""Idempotent AddTPU: a retried request (gateway retry on UNAVAILABLE, lost
reply, worker restart) must never allocate a second slave-pod set.

Slave pods are stamped with the caller's request id; a repeat call with the
same id adopts the survivors of the prior attempt and creates only the
shortfall. Actuation is idempotent (existing device nodes short-circuit,
cgroup sync is whole-set), so the resume path is safe to re-run end to end.
"""

import pytest

from gpumounter_tpu.master.gateway import _RID_RE
from gpumounter_tpu.testing.sim import LiveStack, WorkerRig
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.errors import MountPolicyError
from gpumounter_tpu.worker.grpc_server import WorkerClient


@pytest.fixture
def rig(fake_host):
    r = WorkerRig(fake_host, n_chips=4)
    yield r
    r.close()


RID = "req-abc123"


def test_retry_after_crash_between_allocate_and_reply(rig):
    """The VERDICT scenario: worker dies after creating slave pods but
    before mounting/replying; the retry adopts — exactly one set."""
    pod = rig.sim.kube.get_pod("default", "workload")
    chips, slaves = rig.allocator.get_available_tpus(pod, 4, 4,
                                                     request_id=RID)
    assert len(slaves) == 1          # "crash" here: no mount, no reply

    outcome = rig.service.add_tpu("workload", "default", 4, True,
                                  request_id=RID)
    assert outcome.result == consts.AddResult.SUCCESS
    assert sorted(c.uuid for c in outcome.chips) == \
        sorted(c.uuid for c in chips)
    assert len(rig.sim.slave_pods()) == 1            # adopted, not doubled
    # and the chips actually got actuated on the resume
    assert len(rig.actuator.created) == 4


def test_replay_after_full_success_returns_same_chips(rig):
    """Reply lost after a fully successful entire-mount: the replay is a
    no-op returning the same chips, not a 412 policy denial."""
    first = rig.service.add_tpu("workload", "default", 4, True,
                                request_id=RID)
    assert first.result == consts.AddResult.SUCCESS
    second = rig.service.add_tpu("workload", "default", 4, True,
                                 request_id=RID)
    assert second.result == consts.AddResult.SUCCESS
    assert sorted(c.uuid for c in second.chips) == \
        sorted(c.uuid for c in first.chips)
    assert len(rig.sim.slave_pods()) == 1


def test_replay_of_full_success_records_resumed_event(rig):
    """One logical attach = one TPUAttached in the audit trail; the replay
    that adopted a fully-mounted prior attempt records TPUAttachResumed
    instead of a duplicate TPUAttached."""
    import time
    rig.service.add_tpu("workload", "default", 4, True, request_id=RID)
    rig.service.add_tpu("workload", "default", 4, True, request_id=RID)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and len(rig.sim.kube.events) < 2:
        time.sleep(0.02)
    reasons = [e["reason"] for e in rig.sim.kube.events]
    assert reasons.count("TPUAttached") == 1
    assert reasons.count("TPUAttachResumed") == 1


def test_entire_mount_without_request_id_still_denied_on_repeat(rig):
    """No request id ⇒ no idempotence claim ⇒ the mount policy applies
    unchanged (a genuine second entire-mount is a real conflict)."""
    rig.service.add_tpu("workload", "default", 4, True)
    with pytest.raises(MountPolicyError):
        rig.service.add_tpu("workload", "default", 4, True)


def test_partial_single_mount_resume_creates_only_shortfall(rig):
    """Worker died after creating 1 of 3 single-mount slave pods: the
    retry adopts the survivor and creates exactly 2 more."""
    pod = rig.sim.kube.get_pod("default", "workload")
    rig.allocator.get_available_tpus(pod, 1, 1, request_id=RID)
    assert len(rig.sim.slave_pods()) == 1

    outcome = rig.service.add_tpu("workload", "default", 3, False,
                                  request_id=RID)
    assert outcome.result == consts.AddResult.SUCCESS
    assert len(outcome.chips) == 3
    assert len(rig.sim.slave_pods()) == 3


def test_slave_pods_carry_request_id_label(rig):
    rig.service.add_tpu("workload", "default", 2, False, request_id=RID)
    for pod in rig.sim.slave_pods():
        assert pod["metadata"]["labels"][consts.REQUEST_ID_LABEL_KEY] == RID


def test_grpc_retry_same_request_id_is_idempotent(fake_host):
    """Wire-level: two AddTPU RPCs with the same x-request-id metadata (the
    gateway's retry shape) yield one slave-pod set and identical chips."""
    rig = WorkerRig(fake_host, n_chips=4)
    stack = LiveStack(rig)
    try:
        with WorkerClient(f"127.0.0.1:{stack.grpc_port}") as client:
            first = client.add_tpu("workload", "default", 4, True,
                                   request_id=RID)
            second = client.add_tpu("workload", "default", 4, True,
                                    request_id=RID)
        assert first.result == second.result == 0
        assert list(first.device_ids) == list(second.device_ids)
        assert len(rig.sim.slave_pods()) == 1
    finally:
        stack.close()


def test_failed_resume_preserves_adopted_pods(rig):
    """A retry that fails must not delete the prior attempt's slave pods —
    they may back a fully-mounted attach whose reply was lost; deleting
    them would free chips still in use (double-allocation)."""
    pod = rig.sim.kube.get_pod("default", "workload")
    rig.allocator.get_available_tpus(pod, 1, 1, request_id=RID)
    adopted = rig.sim.slave_pods()
    assert len(adopted) == 1

    # resume asks for 5 singles on a 4-chip node: the fresh pods cannot all
    # schedule -> InsufficientTPU; fresh pods are cleaned up, adoptee stays
    outcome = rig.service.add_tpu("workload", "default", 5, False,
                                  request_id=RID)
    assert outcome.result == consts.AddResult.INSUFFICIENT_TPU
    survivors = rig.sim.slave_pods()
    assert [p["metadata"]["name"] for p in survivors] == \
        [adopted[0]["metadata"]["name"]]


def test_same_request_id_calls_serialized(rig):
    """A retry arriving while the original handler still runs must wait for
    it (fencing) — otherwise its adoption LIST could see a mid-create
    subset and over-allocate."""
    import threading
    import time

    active, overlaps, results = [], [], []
    orig = rig.service._add_tpu

    def slow(*args, **kwargs):
        active.append(1)
        if len(active) > 1:
            overlaps.append(True)
        time.sleep(0.2)
        try:
            return orig(*args, **kwargs)
        finally:
            active.pop()

    rig.service._add_tpu = slow
    threads = [threading.Thread(
        target=lambda: results.append(
            rig.service.add_tpu("workload", "default", 4, True,
                                request_id=RID)))
        for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not overlaps                  # critical sections never overlapped
    assert [r.result for r in results] == [consts.AddResult.SUCCESS] * 2
    assert len(rig.sim.slave_pods()) == 1


def test_lock_table_survives_1024_id_churn(rig):
    """Round-2 VERDICT weak #3: the old LRU evicted the oldest entry
    unconditionally at 1024 live ids — even while held — after which a
    retry of that id got a fresh lock and ran unserialized. Now: churn
    1500 distinct ids while one request is mid-flight, then retry it;
    the retry must still block on the original's lock."""
    import threading
    import time

    release = threading.Event()
    entered = threading.Event()
    order = []

    def holder():
        with rig.service._request_lock("default", "workload", RID):
            entered.set()
            release.wait(5)
            order.append("original")

    t = threading.Thread(target=holder)
    t.start()
    assert entered.wait(5)
    # churn far past the old 1024 bound; each acquires and releases
    for i in range(1500):
        with rig.service._request_lock("default", "workload", f"churn-{i}"):
            pass
    # zero-holder entries are dropped eagerly: only the held one remains
    assert list(rig.service._request_locks._entries) == \
        [("default", "workload", RID)]

    def retry():
        with rig.service._request_lock("default", "workload", RID):
            order.append("retry")

    t2 = threading.Thread(target=retry)
    t2.start()
    time.sleep(0.1)
    assert order == []                   # retry is blocked, not running
    release.set()
    t.join(5)
    t2.join(5)
    assert order == ["original", "retry"]
    assert rig.service._request_locks._entries == {}    # table drained


def test_add_and_remove_same_pod_serialized(rig):
    """Concurrent Add and Remove on one pod must not interleave their
    cgroup syncs — a mount's /dev scan racing a detach can re-grant the
    chip being revoked (r3 review finding)."""
    import threading
    import time

    active, overlaps = [], []

    def tracked(fn):
        def wrapper(*args, **kwargs):
            active.append(1)
            if len(active) > 1:
                overlaps.append(True)
            time.sleep(0.15)
            try:
                return fn(*args, **kwargs)
            finally:
                active.pop()
        return wrapper

    rig.service._add_tpu = tracked(rig.service._add_tpu)
    rig.service._remove_tpu = tracked(rig.service._remove_tpu)

    first = rig.service.add_tpu("workload", "default", 4, True,
                                request_id=RID)
    assert first.result == consts.AddResult.SUCCESS
    uuids = [c.uuid for c in first.chips]

    threads = [
        threading.Thread(target=rig.service.remove_tpu,
                         args=("workload", "default", uuids, False)),
        threading.Thread(target=rig.service.add_tpu,
                         args=("workload", "default", 1, False),
                         kwargs={"request_id": "other-rid"}),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not overlaps


# -- client-supplied X-Request-Id (the HTTP retry contract) -------------------

def test_http_retry_with_client_request_id_is_idempotent(fake_host):
    """The VERDICT scenario at the API boundary: a client whose HTTP reply
    is lost retries with the same X-Request-Id header and gets the same
    chips — one slave-pod set, no double-attach."""
    import json
    import urllib.request

    rig = WorkerRig(fake_host, n_chips=4)
    stack = LiveStack(rig)
    try:
        url = (f"{stack.base}/addtpu/namespace/default/pod/workload"
               f"/tpu/1/isEntireMount/false")
        bodies = []
        for _ in range(2):  # original + lost-reply retry
            req = urllib.request.Request(
                url, headers={"X-Request-Id": RID})
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
                bodies.append(json.loads(resp.read()))
        assert bodies[0]["request_id"] == RID
        assert bodies[1]["request_id"] == RID
        assert bodies[0]["device_ids"] == bodies[1]["device_ids"]
        assert len(rig.sim.slave_pods()) == 1
        # without the header, a repeated single-mount is a NEW attach
        with urllib.request.urlopen(url) as resp:
            extra = json.loads(resp.read())
        assert extra["device_ids"] != bodies[0]["device_ids"]
        assert len(rig.sim.slave_pods()) == 2
    finally:
        stack.close()


def test_invalid_client_request_id_is_400(fake_host):
    rig = WorkerRig(fake_host, n_chips=4)
    stack = LiveStack(rig)
    try:
        status, body = stack.gateway.handle(
            "GET",
            "/addtpu/namespace/default/pod/workload/tpu/1"
            "/isEntireMount/false",
            headers={"X-Request-Id": "bad/slash!"})
        assert status == 400
        assert body["result"] == "BadRequestId"
        assert not rig.sim.slave_pods()     # rejected before any work
        # 64+ chars is not a valid label value either
        status, _ = stack.gateway.handle(
            "GET", "/healthz", headers={"X-Request-Id": "a" * 64})
        assert status == 400
    finally:
        stack.close()


def test_generated_request_id_echoed_without_header(fake_host):
    rig = WorkerRig(fake_host, n_chips=4)
    stack = LiveStack(rig)
    try:
        status, body = stack.gateway.handle(
            "GET",
            "/addtpu/namespace/default/pod/workload/tpu/1"
            "/isEntireMount/false")
        assert status == 200
        assert body["request_id"]           # generated, still echoed
        assert _RID_RE.match(body["request_id"])
    finally:
        stack.close()
