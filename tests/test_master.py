"""Master gateway tests: route parsing, worker discovery caching, and
HTTP-status translation of worker results (ref cmd/GPUMounter-master)."""

import json

import pytest

from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.master.discovery import (WorkerDirectory,
                                             WorkerNotFoundError)
from gpumounter_tpu.master.gateway import MasterGateway, _parse_uuids
from gpumounter_tpu.worker.grpc_server import WorkerClient, build_server

from tests.helpers import WorkerRig, make_target_pod, worker_pod


# -- discovery -----------------------------------------------------------------

def test_directory_resolves_and_caches():
    kube = FakeKubeClient()
    kube.put_pod(worker_pod("node-a", "10.0.0.5"))
    directory = WorkerDirectory(kube, ttl_s=60)
    directory.MISS_REFRESH_INTERVAL_S = 0.0
    assert directory.worker_target("node-a") == "10.0.0.5:1200"
    # cache: a new worker appearing within TTL is still found via forced
    # refresh-on-miss
    kube.put_pod(worker_pod("node-b", "10.0.0.6", name="w2"))
    assert directory.worker_target("node-b") == "10.0.0.6:1200"


def test_directory_miss_refresh_is_rate_limited():
    kube = FakeKubeClient()
    kube.put_pod(worker_pod("node-a", "10.0.0.5"))
    directory = WorkerDirectory(kube, ttl_s=60)
    assert directory.worker_target("node-a") == "10.0.0.5:1200"
    # a worker that appears right after a refresh is not visible until the
    # miss-refresh floor passes — repeated misses must not LIST every call
    kube.put_pod(worker_pod("node-b", "10.0.0.6", name="w2"))
    with pytest.raises(WorkerNotFoundError):
        directory.worker_target("node-b")


def test_directory_unknown_node_raises():
    directory = WorkerDirectory(FakeKubeClient())
    with pytest.raises(WorkerNotFoundError):
        directory.worker_target("nowhere")


def test_directory_ignores_not_ready_workers():
    kube = FakeKubeClient()
    pod = worker_pod("node-a", "10.0.0.5")
    pod["status"]["phase"] = "Pending"
    kube.put_pod(pod)
    directory = WorkerDirectory(kube)
    with pytest.raises(WorkerNotFoundError):
        directory.worker_target("node-a")


# -- uuid parsing --------------------------------------------------------------

def test_parse_uuids_variants():
    assert _parse_uuids(b'{"uuids": ["a", "b"]}', "") == ["a", "b"]
    assert _parse_uuids(b"uuids=a&uuids=b", "") == ["a", "b"]
    assert _parse_uuids(b"uuids=a,b", "") == ["a", "b"]
    assert _parse_uuids(b"", "uuids=a,b") == ["a", "b"]
    assert _parse_uuids(b"", "") == []
    assert _parse_uuids(b"{bad json", "") == []
    # JSON edge cases: string not iterated char-by-char, null/objects safe
    assert _parse_uuids(b'{"uuids": "a,b"}', "") == ["a", "b"]
    assert _parse_uuids(b'{"uuids": null}', "") == []
    assert _parse_uuids(b'{"uuids": 7}', "") == []
    assert _parse_uuids(b'{}', "") == []


def test_directory_invalidate_forces_reresolve():
    kube = FakeKubeClient()
    kube.put_pod(worker_pod("node-a", "10.0.0.5"))
    directory = WorkerDirectory(kube, ttl_s=3600)
    assert directory.worker_target("node-a") == "10.0.0.5:1200"
    # worker pod restarted with a new IP; TTL is far away
    kube.delete_pod("kube-system", "w1")
    kube.put_pod(worker_pod("node-a", "10.0.0.9"))
    directory.invalidate("node-a")
    assert directory.worker_target("node-a") == "10.0.0.9:1200"


# -- gateway over a live worker ------------------------------------------------

@pytest.fixture
def stack(fake_host):
    """WorkerRig + live gRPC worker + gateway whose directory points at it."""
    rig = WorkerRig(fake_host)
    server, port = build_server(rig.service, port=0, address="127.0.0.1")
    server.start()

    master_kube = FakeKubeClient()
    master_kube.put_pod(worker_pod("node-a", "127.0.0.1"))
    master_kube.put_pod(make_target_pod())      # master resolves pod→node
    directory = WorkerDirectory(master_kube, grpc_port=port)
    gateway = MasterGateway(master_kube, directory)
    yield rig, gateway
    server.stop(grace=0)


def test_add_route_success(stack):
    rig, gateway = stack
    status, body = gateway.handle(
        "GET",
        "/addtpu/namespace/default/pod/workload/tpu/2/isEntireMount/false")
    assert status == 200
    assert body["result"] == "SUCCESS"
    assert len(body["device_ids"]) == 2
    assert len(rig.sim.slave_pods()) == 2


def test_add_route_insufficient_is_503(stack):
    _, gateway = stack
    status, body = gateway.handle(
        "GET",
        "/addtpu/namespace/default/pod/workload/tpu/9/isEntireMount/false")
    assert status == 503
    assert body["result"] == "INSUFFICIENT_TPU"


def test_add_route_missing_pod_is_404(stack):
    _, gateway = stack
    status, body = gateway.handle(
        "GET", "/addtpu/namespace/default/pod/ghost/tpu/1/isEntireMount/true")
    assert status == 404


def test_policy_violation_is_412(stack):
    _, gateway = stack
    gateway.handle(
        "GET",
        "/addtpu/namespace/default/pod/workload/tpu/4/isEntireMount/true")
    status, body = gateway.handle(
        "GET",
        "/addtpu/namespace/default/pod/workload/tpu/1/isEntireMount/false")
    assert status == 412


def test_remove_route_roundtrip(stack):
    rig, gateway = stack
    _, body = gateway.handle(
        "GET",
        "/addtpu/namespace/default/pod/workload/tpu/2/isEntireMount/false")
    uuids = ",".join(body["device_ids"])
    status, body = gateway.handle(
        "POST", "/removetpu/namespace/default/pod/workload/force/false",
        f"uuids={uuids}".encode())
    assert status == 200
    assert body["result"] == "SUCCESS"
    assert rig.sim.slave_pods() == []


def test_remove_busy_is_409_with_pids(stack):
    rig, gateway = stack
    _, body = gateway.handle(
        "GET",
        "/addtpu/namespace/default/pod/workload/tpu/1/isEntireMount/false")
    path = body["device_paths"][0]
    rig.sim.enumerator.busy_pids = {path: [rig.pid]}
    status, body = gateway.handle(
        "POST", "/removetpu/namespace/default/pod/workload/force/false",
        json.dumps({"uuids": body["device_ids"]}).encode())
    assert status == 409
    assert body["busy_pids"] == [rig.pid]


def test_no_worker_on_node_is_502(stack, fake_host):
    rig, gateway = stack
    gateway.directory._by_node.clear()
    gateway.directory.kube = FakeKubeClient()       # directory sees no workers
    status, body = gateway.handle(
        "GET",
        "/addtpu/namespace/default/pod/workload/tpu/1/isEntireMount/false")
    assert status == 502
    assert body["result"] == "WorkerNotFound"


def test_unknown_route_404(stack):
    _, gateway = stack
    status, _ = gateway.handle("GET", "/nope")
    assert status == 404
    status, _ = gateway.handle("GET", "/healthz")
    assert status == 200


def test_reference_route_aliases(stack):
    """The reference's exact route shapes (/addgpu/.../gpu/:n/...,
    /removegpu/.../force/:b — cmd/GPUMounter-master/main.go:233-234) are
    drop-in aliases: a GPUMounter user's scripts work unchanged."""
    rig, gw = stack
    status, body = gw.handle(
        "GET", "/addgpu/namespace/default/pod/workload/gpu/2"
               "/isEntireMount/true")
    assert status == 200 and body["result"] == "SUCCESS"
    assert len(body["device_ids"]) == 2
    status, body = gw.handle(
        "POST", "/removegpu/namespace/default/pod/workload/force/false",
        body=b"uuids=" + ",".join(body["device_ids"]).encode())
    assert status == 200 and body["result"] == "SUCCESS"


def test_reference_alias_parsebool_variants(stack):
    """strconv.ParseBool parity on alias routes (ref main.go:38,140):
    1/T/True work; garbage gets 400, not 404."""
    rig, gw = stack
    status, body = gw.handle(
        "GET", "/addgpu/namespace/default/pod/workload/gpu/1"
               "/isEntireMount/False")
    assert status == 200 and body["result"] == "SUCCESS"
    status, body = gw.handle(
        "POST", "/removegpu/namespace/default/pod/workload/force/0",
        body=b"uuids=" + body["device_ids"][0].encode())
    assert status == 200 and body["result"] == "SUCCESS"
    status, body = gw.handle(
        "GET", "/addgpu/namespace/default/pod/workload/gpu/1"
               "/isEntireMount/maybe")
    assert status == 400 and body["result"] == "BadRequest"


def test_node_status_route(stack):
    """/nodestatus/node/:node — node-wide inventory with free/total counts,
    reflecting allocation changes."""
    rig, gw = stack
    status, body = gw.handle("GET", "/nodestatus/node/node-a")
    assert status == 200
    assert body["free"] == 4 and body["total"] == 4
    gw.handle("GET",
              "/addtpu/namespace/default/pod/workload/tpu/2"
              "/isEntireMount/false")
    status, body = gw.handle("GET", "/nodestatus/node/node-a")
    assert body["free"] == 2
    allocated = [c for c in body["chips"] if c["state"] == "ALLOCATED"]
    assert len(allocated) == 2
    assert all(c["namespace"] == "tpu-pool" for c in allocated)
    # typo'd node (doesn't exist in the cluster): client error, 404
    status, body = gw.handle("GET", "/nodestatus/node/nope")
    assert status == 404 and body["result"] == "NodeNotFound"
    # real node with no worker on it: genuine 502
    gw.kube.put_node({"metadata": {"name": "workerless"}})
    status, body = gw.handle("GET", "/nodestatus/node/workerless")
    assert status == 502 and body["result"] == "WorkerNotFound"


def test_node_status_reports_gke_topology_labels(stack):
    """On a labeled GKE node, accelerator/topology come from node labels —
    present even for FREE chips (no allocation required)."""
    from gpumounter_tpu.testing.sim import make_tpu_node
    rig, gw = stack
    rig.sim.kube.put_node(make_tpu_node(name="node-a"))
    status, body = gw.handle("GET", "/nodestatus/node/node-a")
    assert status == 200
    assert all(c["accelerator"] == "tpu-v5-lite-podslice"
               and c["topology"] == "2x2" for c in body["chips"])


def test_version_route(stack):
    import gpumounter_tpu
    rig, gw = stack
    status, body = gw.handle("GET", "/version")
    assert status == 200 and body["version"] == gpumounter_tpu.__version__
