"""Async-capable worker service (utils/parking.py + grpc_server mode):
the parking executor carries many in-flight RPCs over a small ACTIVE
budget — slow waits (slave-pod scheduling, informer fences, kubelet
lag, keyed locks) release their slot — while the service semantics the
restructure must preserve (drain's in-flight tokens, per-rid
idempotency, per-pod serialisation) keep holding. The thread-pool path
stays the byte-for-byte default-off fallback."""

import threading
import time

import grpc
import pytest

from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import Settings
from gpumounter_tpu.utils.parking import ParkingExecutor, parked


# -- executor unit -------------------------------------------------------------

def test_active_budget_bounds_running_threads():
    ex = ParkingExecutor(max_active=2)
    running = []
    lock = threading.Lock()
    peak = [0]

    def task():
        with lock:
            running.append(1)
            peak[0] = max(peak[0], len(running))
        time.sleep(0.05)
        with lock:
            running.pop()

    futures = [ex.submit(task) for _ in range(8)]
    for f in futures:
        f.result(timeout=10)
    assert peak[0] <= 2
    assert ex.status()["peak_active"] <= 2
    ex.shutdown()


def test_parked_waits_release_their_slot():
    """The point of the whole mechanism: 16 RPC-shaped tasks all parked
    in a wait at once over an active budget of 2 — in-flight capacity
    decoupled from the thread budget."""
    ex = ParkingExecutor(max_active=2)
    release = threading.Event()

    def task():
        with parked("test-wait"):
            release.wait(timeout=30)
        return "done"

    futures = [ex.submit(task) for _ in range(16)]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and ex.status()["parked"] < 16:
        time.sleep(0.005)
    status = ex.status()
    assert status["parked"] == 16, status   # 16 in flight, budget 2
    assert status["active"] == 0
    release.set()
    assert [f.result(timeout=10) for f in futures] == ["done"] * 16
    assert ex.status()["peak_parked"] == 16
    ex.shutdown()


def test_parked_is_reentrant_and_noop_off_executor():
    # off-executor: plain passthrough (the legacy-server byte-for-byte
    # guarantee — every instrumented wait site runs this path there)
    with parked("outer"):
        with parked("inner"):
            pass
    ex = ParkingExecutor(max_active=1)
    depths = {}

    def task():
        with parked("outer"):
            depths["outer"] = ex.status()["parked"]
            with parked("inner"):
                depths["inner"] = ex.status()["parked"]
        return True

    assert ex.submit(task).result(timeout=10)
    assert depths == {"outer": 1, "inner": 1}   # released exactly once
    ex.shutdown()


def test_unpark_reacquires_within_the_budget():
    """A thread leaving its wait queues for a slot like anyone else —
    the budget holds through the park/unpark cycle."""
    ex = ParkingExecutor(max_active=1)
    gate = threading.Event()
    order = []

    def parker():
        with parked("w"):
            gate.wait(timeout=30)
        order.append("parker-resumed")

    def runner():
        order.append("runner-ran")
        gate.set()
        time.sleep(0.05)        # holds the ONE slot while gate is set

    f1 = ex.submit(parker)
    while ex.status()["parked"] < 1:
        time.sleep(0.005)
    f2 = ex.submit(runner)      # takes the slot the parker released
    f1.result(timeout=10)
    f2.result(timeout=10)
    assert order == ["runner-ran", "parker-resumed"]
    ex.shutdown()


# -- the worker service over the parking server --------------------------------

@pytest.fixture
def parking_stack(fake_host):
    """A live gRPC worker in parking mode with an ACTIVE budget of 2
    over a sim whose kubelet lags device assignment — the wait the
    allocator parks through."""
    from gpumounter_tpu.testing.sim import WorkerRig
    from gpumounter_tpu.worker.grpc_server import WorkerClient, build_server
    rig = WorkerRig(fake_host, n_chips=8, kubelet_lag_s=0.6,
                    informer=True)
    server, port = build_server(rig.service, port=0, address="127.0.0.1",
                                max_workers=2, mode="parking")
    server.start()
    client = WorkerClient(f"127.0.0.1:{port}", timeout_s=60)
    try:
        yield rig, server, client, port
    finally:
        client.close()
        server.stop(grace=0)
        rig.close()


def test_concurrent_slow_attaches_overlap_beyond_the_budget(
        parking_stack):
    """6 attaches whose kubelet lag dominates, budget 2: under the old
    fixed pool they would run 2 at a time (>= 3 lag windows); parking
    overlaps them all. Pinned structurally (peak_parked) AND by wall
    clock staying under the serialized bound."""
    rig, server, _, port = parking_stack
    pods = []
    for i in range(6):
        pod = rig.sim.add_target_pod(name=f"load-{i}", uid=f"uid-l{i}")
        rig.provision_container(pod)
        pods.append(f"load-{i}")
    results = {}

    def one(pod):
        from gpumounter_tpu.worker.grpc_server import WorkerClient
        with WorkerClient(f"127.0.0.1:{port}", timeout_s=60) as c:
            results[pod] = c.add_tpu(pod, "default", 1, False,
                                     request_id=f"rid-{pod}")

    t0 = time.monotonic()
    threads = [threading.Thread(target=one, args=(p,)) for p in pods]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    wall = time.monotonic() - t0
    assert len(results) == 6
    for pod, resp in results.items():
        assert consts.AddResult(resp.result) == consts.AddResult.SUCCESS, \
            (pod, resp)
    # serialized bound: ceil(6/2) lag windows = 1.8s; overlapped runs
    # pay ~one window + overhead
    assert wall < 1.7, f"parking attaches serialized: {wall:.2f}s"
    assert server.parking_executor.peak_parked >= 3, \
        server.parking_executor.status()


def test_drain_tokens_survive_the_parking_restructure(parking_stack):
    """The drain gate still runs on the handler path: a draining worker
    refuses NEW attaches with the draining: detail through the parking
    server exactly like the thread-pool one."""
    from gpumounter_tpu.worker.drain import DrainController
    rig, _, client, _port = parking_stack
    drain = DrainController(rig.sim.node)
    rig.service.drain = drain
    drain.begin("test")
    with pytest.raises(grpc.RpcError) as err:
        client.add_tpu("workload", "default", 1, False,
                       request_id="rid-drained")
    assert err.value.code() == grpc.StatusCode.UNAVAILABLE
    assert err.value.details().startswith(consts.DRAINING_DETAIL_PREFIX)
    assert drain.status()["refused"] == 1
    assert drain.status()["inflight"] == 0      # token released


def test_per_rid_idempotency_survives_the_parking_restructure(
        parking_stack):
    """Two concurrent attaches under ONE request id serialize on the
    request lock (a parked wait, budget-exempt) and resolve to the SAME
    grant — zero double-actuation, the retry contract the gateway
    relies on."""
    rig, server, _, port = parking_stack
    results = []

    def one():
        from gpumounter_tpu.worker.grpc_server import WorkerClient
        with WorkerClient(f"127.0.0.1:{port}", timeout_s=60) as c:
            results.append(c.add_tpu("workload", "default", 2, True,
                                     request_id="rid-same"))

    threads = [threading.Thread(target=one) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(results) == 2
    ids = [sorted(r.device_ids) for r in results]
    assert ids[0] == ids[1] and len(ids[0]) == 2, ids
    # ONE slave-pod set: the retry adopted, it did not double-attach
    assert len(rig.sim.slave_pods()) == 1


# -- knobs / off-path ----------------------------------------------------------

def test_threadpool_remains_the_default_off_path(fake_host):
    from gpumounter_tpu.testing.sim import WorkerRig
    from gpumounter_tpu.worker.grpc_server import build_server
    rig = WorkerRig(fake_host)
    server, _ = build_server(rig.service, port=0, address="127.0.0.1")
    assert server.parking_executor is None      # the off-path pin
    server.stop(grace=0)
    with pytest.raises(ValueError):
        build_server(rig.service, port=0, mode="warp")
    rig.close()


def test_grpc_knobs_plumb_through_the_rigs(fake_host):
    """The Settings → WorkerRig → LiveStack plumbing mirrors
    worker/main.py's Settings → build_server wiring: a rig built with
    the knobs carries them on its Settings, and a LiveStack deferring
    to settings builds the matching server."""
    from gpumounter_tpu.testing.sim import LiveStack, WorkerRig
    rig = WorkerRig(fake_host, grpc_workers=3, grpc_async=True)
    assert rig.sim.settings.grpc_workers == 3
    assert rig.sim.settings.grpc_async is True
    stack = LiveStack(rig, grpc_workers=None, grpc_mode="settings")
    try:
        executor = stack.grpc_server.parking_executor
        assert executor is not None and executor.max_active == 3
    finally:
        stack.close()


def test_grpc_knobs_plumb_from_env():
    assert Settings().grpc_async is False       # direct construction
    assert Settings().grpc_workers == consts.DEFAULT_GRPC_WORKERS
    env = Settings.from_env({})
    assert env.grpc_async is True               # production default ON
    assert env.grpc_workers == consts.DEFAULT_GRPC_WORKERS
    off = Settings.from_env({"TPU_GRPC_ASYNC": "0",
                             "TPU_GRPC_WORKERS": "32"})
    assert off.grpc_async is False and off.grpc_workers == 32
    with pytest.raises(ValueError):
        Settings.from_env({"TPU_GRPC_WORKERS": "0"})
    with pytest.raises(ValueError):
        Settings.from_env({"TPU_GRPC_WORKERS": "64",
                           "TPU_GRPC_MAX_PARKED": "8"})
