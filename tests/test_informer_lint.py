"""Informer lint (AST-based, à la test_retry_lint): hot-path modules must
not read pods from the apiserver client directly — every pod read goes
through the informer handle (``self.reads``, k8s/informer.py), so the
zero-LIST attach budget cannot silently regress by someone adding a
``self.kube.list_pods(...)`` call back.

Writes (create/patch/delete) stay on the client by design — they must hit
the apiserver — and the informer module itself plus the background
reconciler (not on the attach path) are the only non-client holders of
raw list/watch calls.
"""

import ast
import inspect

import gpumounter_tpu.allocator.allocator as allocator_mod
import gpumounter_tpu.k8s.informer as informer_mod
import gpumounter_tpu.worker.pool as pool_mod
import gpumounter_tpu.worker.service as service_mod

HOT_PATH_MODULES = (allocator_mod, pool_mod, service_mod)

READ_VERBS = {"list_pods", "list_pods_with_version", "watch_pods"}


def _receiver_name(node: ast.AST) -> str:
    """Best-effort dotted receiver of an attribute access:
    ``self.kube.list_pods`` -> "self.kube"."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _read_calls_on_kube(module) -> list[str]:
    tree = ast.parse(inspect.getsource(module))
    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute):
            continue
        if node.attr not in READ_VERBS:
            continue
        receiver = _receiver_name(node.value)
        # any receiver that IS (or holds) the raw client: self.kube,
        # kube, sim.kube, self.sim.kube ...
        if receiver == "kube" or receiver.endswith(".kube"):
            offenders.append(f"{module.__name__}: {receiver}.{node.attr}")
    return offenders


def test_hot_path_modules_never_list_pods_on_the_client():
    offenders = [o for module in HOT_PATH_MODULES
                 for o in _read_calls_on_kube(module)]
    assert offenders == [], \
        f"pod reads bypass the informer handle: {offenders}"


def test_hot_path_modules_read_through_the_handle():
    """The positive half: each hot-path module actually holds and uses a
    ``reads`` handle (not just avoids the client)."""
    for module in HOT_PATH_MODULES:
        tree = ast.parse(inspect.getsource(module))
        uses = [n for n in ast.walk(tree)
                if isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Attribute)
                and n.value.attr == "reads"]
        assert uses, f"{module.__name__} never reads through the handle"


def test_informer_owns_the_shared_list_watch():
    """Inside k8s/informer.py, raw client list/watch calls live in exactly
    the stream machinery: the informer's seed/loop and the legacy
    (informer-less) wait fallback — nowhere else."""
    tree = ast.parse(inspect.getsource(informer_mod))
    holders = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for inner in ast.walk(node):
                if isinstance(inner, ast.Attribute) \
                        and inner.attr in READ_VERBS \
                        and _receiver_name(inner.value).endswith("kube"):
                    holders.add(node.name)
    assert holders <= {"_resync", "_run", "_wait_pods_watch", "sync",
                       "get_pod", "list_pods", "list_pods_with_version"}, \
        holders


def test_wait_state_machines_use_the_handle():
    """The allocator's create/delete waits and the pool's refill wait ride
    the shared stream (reads.wait_pods), not private watches."""
    import textwrap
    for module, method in ((allocator_mod, "_wait_running"),
                           (allocator_mod, "_wait_deleted"),
                           (pool_mod, "_await_running")):
        cls = {"allocator": "TPUAllocator",
               "pool": "PoolManager"}[module.__name__.rsplit(".", 1)[-1]]
        source = textwrap.dedent(inspect.getsource(
            getattr(getattr(module, cls), method)))
        tree = ast.parse(source)
        names = {n.attr for n in ast.walk(tree)
                 if isinstance(n, ast.Attribute)}
        assert "wait_pods" in names, f"{cls}.{method} bypasses wait_pods"
