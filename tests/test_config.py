import pytest

from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import Settings


def test_settings_defaults():
    s = Settings.from_env({})
    assert s.pool_namespace == consts.DEFAULT_POOL_NAMESPACE
    assert s.cgroup_driver == "systemd"
    assert s.resource_name == consts.TPU_RESOURCE_NAME
    assert s.allocation_timeout_s == 120.0


def test_settings_env_overrides():
    s = Settings.from_env({
        consts.ENV_POOL_NAMESPACE: "my-pool",
        consts.ENV_CGROUP_DRIVER: "cgroupfs",
        "NODE_NAME": "node-1",
        "TPU_ALLOCATION_TIMEOUT_S": "7.5",
    })
    assert s.pool_namespace == "my-pool"
    assert s.cgroup_driver == "cgroupfs"
    assert s.node_name == "node-1"
    assert s.allocation_timeout_s == 7.5


def test_settings_rejects_unknown_cgroup_driver():
    # ref cgroup.go:78-84: only systemd|cgroupfs are valid
    with pytest.raises(ValueError):
        Settings.from_env({consts.ENV_CGROUP_DRIVER: "bogus"})


def test_remove_result_wire_parity():
    # ref api.proto:32-41 skips enum tag 3
    assert consts.RemoveResult.TPU_NOT_FOUND == 4
