import pytest

from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import Settings


def test_settings_defaults():
    s = Settings.from_env({})
    assert s.pool_namespace == consts.DEFAULT_POOL_NAMESPACE
    assert s.cgroup_driver == "systemd"
    assert s.resource_name == consts.TPU_RESOURCE_NAME
    assert s.allocation_timeout_s == 120.0


def test_settings_env_overrides():
    s = Settings.from_env({
        consts.ENV_POOL_NAMESPACE: "my-pool",
        consts.ENV_CGROUP_DRIVER: "cgroupfs",
        "NODE_NAME": "node-1",
        "TPU_ALLOCATION_TIMEOUT_S": "7.5",
    })
    assert s.pool_namespace == "my-pool"
    assert s.cgroup_driver == "cgroupfs"
    assert s.node_name == "node-1"
    assert s.allocation_timeout_s == 7.5


def test_parse_tenant_quotas():
    from gpumounter_tpu.utils.config import parse_tenant_quotas
    assert parse_tenant_quotas("teamA:16,teamB:8,*:4") == \
        {"teamA": 16, "teamB": 8, "*": 4}
    assert parse_tenant_quotas("") == {}
    assert parse_tenant_quotas(" teamA:1 , ") == {"teamA": 1}
    for bad in ("teamA", "teamA:x", ":4", "a:1,a:2", "a:-1"):
        with pytest.raises(ValueError):
            parse_tenant_quotas(bad)


def test_broker_settings_from_env():
    s = Settings.from_env({
        consts.ENV_QUOTAS: "teamA:16,*:4",
        consts.ENV_QUOTA_BURST: "1.5",
        consts.ENV_LEASE_TTL_S: "3600",
        consts.ENV_QUEUE_TIMEOUT_S: "30",
        consts.ENV_QUEUE_DEPTH: "8",
    })
    assert s.tenant_quotas == {"teamA": 16, "*": 4}
    assert s.quota_burst == 1.5
    assert s.lease_ttl_s == 3600.0
    assert s.queue_timeout_s == 30.0
    assert s.queue_depth == 8
    # defaults preserve the historical behavior exactly
    s = Settings.from_env({})
    assert s.tenant_quotas == {} and s.quota_burst == 1.0
    assert s.lease_ttl_s == 0.0 and s.queue_timeout_s == 0.0
    # a burst below 1.0 would make quotas deny what it claims to grant
    with pytest.raises(ValueError):
        Settings.from_env({consts.ENV_QUOTA_BURST: "0.5"})


def test_broker_config_maps_settings():
    from gpumounter_tpu.master.admission import BrokerConfig
    s = Settings.from_env({consts.ENV_QUOTAS: "t:2",
                           consts.ENV_LEASE_TTL_S: "60",
                           consts.ENV_POOL_NAMESPACE: "my-pool"})
    config = BrokerConfig.from_settings(s)
    assert config.quotas == {"t": 2}
    assert config.lease_ttl_s == 60.0
    assert config.pool_namespace == "my-pool"
    assert config.resource_name == s.resource_name


def test_settings_rejects_unknown_cgroup_driver():
    # ref cgroup.go:78-84: only systemd|cgroupfs are valid
    with pytest.raises(ValueError):
        Settings.from_env({consts.ENV_CGROUP_DRIVER: "bogus"})


def test_remove_result_wire_parity():
    # ref api.proto:32-41 skips enum tag 3
    assert consts.RemoveResult.TPU_NOT_FOUND == 4


def test_json_log_format(monkeypatch, capsys):
    import logging
    from gpumounter_tpu.utils import log as log_mod
    monkeypatch.setenv("LOG_FORMAT", "json")
    monkeypatch.setattr(log_mod, "_configured", False)
    root = logging.getLogger("tpumounter")
    old_handlers = list(root.handlers)
    for h in old_handlers:
        root.removeHandler(h)
    try:
        log_mod.init_logger()
        log_mod.get_logger("test").info("hello %s", "world")
        out = capsys.readouterr().out.strip().splitlines()[-1]
        import json
        obj = json.loads(out)
        assert obj["message"] == "hello world"
        assert obj["level"] == "INFO"
        assert obj["logger"] == "tpumounter.test"
    finally:
        for h in list(root.handlers):
            root.removeHandler(h)
        for h in old_handlers:
            root.addHandler(h)
        monkeypatch.setattr(log_mod, "_configured", True)


def test_ha_settings_from_env():
    s = Settings.from_env({
        consts.ENV_MASTER_SHARDS: "4",
        consts.ENV_ELECTION: "1",
        consts.ENV_ELECTION_RENEW_S: "0.5",
        consts.ENV_ELECTION_TTL_S: "1.5",
        consts.ENV_INTENT_STORE: "1",
        consts.ENV_REPLICA_ID: "master-abc",
        consts.ENV_ADVERTISE_URL: "http://10.0.0.7:8080",
        consts.ENV_SHARD_FORWARD: "redirect",
    })
    assert s.master_shards == 4
    assert s.election_enabled and s.intent_store_enabled
    assert s.election_renew_s == 0.5 and s.election_ttl_s == 1.5
    assert s.replica_id == "master-abc"
    assert s.advertise_url == "http://10.0.0.7:8080"
    assert s.shard_forward == "redirect"
    # ALL defaults = single-master PR 7 semantics (docs/guide/HA.md)
    s = Settings.from_env({})
    assert s.master_shards == 1
    assert not s.election_enabled and not s.intent_store_enabled
    assert s.shard_forward == "proxy"
    assert s.election_renew_s == consts.DEFAULT_ELECTION_RENEW_S
    assert s.election_ttl_s == consts.DEFAULT_ELECTION_TTL_S
    # misconfigurations that would flap leadership or split the ring
    with pytest.raises(ValueError):
        Settings.from_env({consts.ENV_MASTER_SHARDS: "0"})
    with pytest.raises(ValueError):
        # a lock that expires between renewals flaps every interval
        Settings.from_env({consts.ENV_ELECTION_RENEW_S: "5",
                           consts.ENV_ELECTION_TTL_S: "2"})
    with pytest.raises(ValueError):
        Settings.from_env({consts.ENV_SHARD_FORWARD: "broadcast"})


def test_ha_config_maps_settings():
    from gpumounter_tpu.master.shardring import HAConfig
    s = Settings.from_env({
        consts.ENV_MASTER_SHARDS: "2",
        consts.ENV_ELECTION: "1",
        consts.ENV_INTENT_STORE: "1",
        consts.ENV_REPLICA_ID: "m-0",
        consts.ENV_ADVERTISE_URL: "http://m-0:8080",
        consts.ENV_POOL_NAMESPACE: "my-pool",
    })
    ha = HAConfig.from_settings(s)
    assert ha.shards == 2 and ha.election and ha.store
    assert ha.replica == "m-0"
    assert ha.advertise_url == "http://m-0:8080"
    assert ha.namespace == "my-pool"
    assert ha.enabled
    # defaults: disabled plane, replica falls back to the hostname
    ha = HAConfig.from_settings(Settings.from_env({}))
    assert not ha.enabled
    assert ha.replica            # never empty — lock records need identity
