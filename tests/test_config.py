import pytest

from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import Settings


def test_settings_defaults():
    s = Settings.from_env({})
    assert s.pool_namespace == consts.DEFAULT_POOL_NAMESPACE
    assert s.cgroup_driver == "systemd"
    assert s.resource_name == consts.TPU_RESOURCE_NAME
    assert s.allocation_timeout_s == 120.0


def test_settings_env_overrides():
    s = Settings.from_env({
        consts.ENV_POOL_NAMESPACE: "my-pool",
        consts.ENV_CGROUP_DRIVER: "cgroupfs",
        "NODE_NAME": "node-1",
        "TPU_ALLOCATION_TIMEOUT_S": "7.5",
    })
    assert s.pool_namespace == "my-pool"
    assert s.cgroup_driver == "cgroupfs"
    assert s.node_name == "node-1"
    assert s.allocation_timeout_s == 7.5


def test_settings_rejects_unknown_cgroup_driver():
    # ref cgroup.go:78-84: only systemd|cgroupfs are valid
    with pytest.raises(ValueError):
        Settings.from_env({consts.ENV_CGROUP_DRIVER: "bogus"})


def test_remove_result_wire_parity():
    # ref api.proto:32-41 skips enum tag 3
    assert consts.RemoveResult.TPU_NOT_FOUND == 4


def test_json_log_format(monkeypatch, capsys):
    import logging
    from gpumounter_tpu.utils import log as log_mod
    monkeypatch.setenv("LOG_FORMAT", "json")
    monkeypatch.setattr(log_mod, "_configured", False)
    root = logging.getLogger("tpumounter")
    old_handlers = list(root.handlers)
    for h in old_handlers:
        root.removeHandler(h)
    try:
        log_mod.init_logger()
        log_mod.get_logger("test").info("hello %s", "world")
        out = capsys.readouterr().out.strip().splitlines()[-1]
        import json
        obj = json.loads(out)
        assert obj["message"] == "hello world"
        assert obj["level"] == "INFO"
        assert obj["logger"] == "tpumounter.test"
    finally:
        for h in list(root.handlers):
            root.removeHandler(h)
        for h in old_handlers:
            root.addHandler(h)
        monkeypatch.setattr(log_mod, "_configured", True)
