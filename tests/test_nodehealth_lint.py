"""AST lints for the node failure domain (ISSUE 13).

The subsystem's safety argument rests on two structural rules that a
refactor could silently break:

1. **One eviction seam.** Health-driven lease removal must cross
   ``AttachBroker.fence_lease`` — the ONE site that cleans cluster
   ground truth (slave pods), counts, events and capacity-signals.
   Health code (master/nodehealth.py, the broker's node-down handling,
   slice repair) reaching into the :class:`LeaseTable` directly would
   evict the lease while leaving ground truth granting chips — the
   zombie-rejoin convergence would then RESTORE the fenced grant.
2. **No silent transitions.** Every node health-state change goes
   through ``NodeHealthTracker._set_state``, which pairs the paired
   lifecycle event with the gauge move — an operator tailing /eventz
   must see every cordon/fence decision the control plane made.
"""

import ast
import os

import gpumounter_tpu
from gpumounter_tpu.master import nodehealth

_PKG = os.path.dirname(gpumounter_tpu.__file__)

# LeaseTable mutation surface no health code may touch directly.
_EVICTION_ATTRS = {"drop", "evict_where", "release", "merge_records",
                   "record", "rederive"}


def _parse(rel_path):
    path = os.path.join(_PKG, rel_path)
    with open(path) as f:
        return ast.parse(f.read(), filename=path)


def _functions(tree):
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    out[f"{node.name}.{item.name}"] = item
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _called_attrs(node):
    for call in ast.walk(node):
        if isinstance(call, ast.Call) and isinstance(call.func,
                                                     ast.Attribute):
            yield call.func


def test_nodehealth_module_never_touches_the_lease_table():
    tree = _parse("master/nodehealth.py")
    offenders = [f"{fn.attr} (line {fn.lineno})"
                 for fn in _called_attrs(tree)
                 if fn.attr in _EVICTION_ATTRS]
    assert not offenders, \
        "master/nodehealth.py performs lease-table mutations directly " \
        f"({offenders}); health code must go through the broker's " \
        "fence_lease / handle_node_down seam"
    names = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
    assert "LeaseTable" not in names, \
        "master/nodehealth.py references LeaseTable — the tracker " \
        "judges nodes, the broker owns leases"


def test_every_health_state_transition_goes_through_set_state():
    tree = _parse("master/nodehealth.py")
    funcs = _functions(tree)
    setter = funcs.get("NodeHealthTracker._set_state")
    assert setter is not None, "_set_state vanished — update this lint"
    # the seam itself emits the paired event AND moves the gauge
    assert any(fn.attr == "emit" for fn in _called_attrs(setter)), \
        "_set_state no longer emits the paired lifecycle event"
    gauge_moved = any(
        fn.attr == "set" and isinstance(fn.value, ast.Attribute)
        and fn.value.attr == "node_health_state"
        for fn in _called_attrs(setter))
    assert gauge_moved, \
        "_set_state no longer moves node_health_state{node}"
    # ...and no OTHER site writes record.state
    for name, func in funcs.items():
        if name.split(".")[-1] in ("_set_state", "__init__"):
            continue
        for node in ast.walk(func):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    assert not (isinstance(target, ast.Attribute)
                                and target.attr == "state"), \
                        f"{name} writes .state outside _set_state " \
                        "(silent health transition)"


def test_broker_node_down_path_evicts_only_through_fence_lease():
    funcs = _functions(_parse("master/admission.py"))
    fence = funcs.get("AttachBroker.fence_lease")
    assert fence is not None, "fence_lease vanished — update this lint"
    attrs = {fn.attr for fn in _called_attrs(fence)}
    # the seam does ALL of: evict, clean cluster truth, count, event,
    # wake the queue
    for wanted in ("drop", "inc", "emit", "signal_capacity",
                   "_fence_cleanup"):
        assert wanted in attrs, \
            f"fence_lease no longer calls {wanted} — the seam's " \
            "contract eroded"
    for name in ("AttachBroker.handle_node_down",):
        func = funcs[name]
        assert not any(fn.attr in _EVICTION_ATTRS
                       for fn in _called_attrs(func)), \
            f"{name} mutates the lease table directly instead of " \
            "crossing fence_lease"
        assert any(fn.attr == "fence_lease"
                   for fn in _called_attrs(func)), \
            f"{name} no longer crosses the fencing seam"
    # the reaper's unreachable-node escape also fences, never drops
    reap = funcs["AttachBroker._reap"]
    assert any(fn.attr == "fence_lease" for fn in _called_attrs(reap)), \
        "_reap lost its fence-after-N-failures escape (dead workers " \
        "would be retried forever)"


def test_slice_repair_evicts_only_through_the_seam_and_pairs_events():
    funcs = _functions(_parse("master/slicetxn.py"))
    for name in ("SliceTxnManager.repair_group",
                 "SliceTxnManager._teardown_group"):
        func = funcs[name]
        called = {fn.attr for fn in _called_attrs(func)}
        assert "drop" not in called and "evict_where" not in called, \
            f"{name} evicts leases directly instead of fence_lease/" \
            "release"
        assert "fence_lease" in called, \
            f"{name} no longer crosses the fencing seam"
    # migration is the NON-destructive half: it must never fence (the
    # node is alive) nor evict directly — leavers detach cleanly or
    # stay until the drain/dead path finishes them
    migrate = {fn.attr for fn in _called_attrs(
        funcs["SliceTxnManager._migrate"])}
    assert "fence_lease" not in migrate and "drop" not in migrate \
        and "evict_where" not in migrate, \
        "_migrate fences/evicts — a proactive migration off a LIVE " \
        "node must never revoke one-way"
    # every slice_repairs counter move pairs with a slice_repair event
    for name, func in funcs.items():
        hits = [fn for fn in _called_attrs(func)
                if fn.attr == "inc" and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "slice_repairs"]
        if hits:
            assert any(fn.attr == "emit" for fn in _called_attrs(func)), \
                f"{name} counts a repair outcome without emitting the " \
                "paired slice_repair event"


def test_subsystem_is_default_on_and_gateway_gates_on_the_knob():
    assert nodehealth.enabled({}) is True
    assert nodehealth.enabled({"TPU_NODE_HEALTH": "0"}) is False
    with open(os.path.join(_PKG, "master", "gateway.py")) as f:
        source = f.read()
    assert "nodehealth.enabled()" in source, \
        "gateway no longer gates the tracker on nodehealth.enabled()"


def test_worker_add_path_crosses_the_drain_gate():
    funcs = _functions(_parse("worker/service.py"))
    add = funcs["TPUMountService.add_tpu"]
    assert any(fn.attr == "inflight" for fn in _called_attrs(add)), \
        "add_tpu no longer crosses the drain gate (a draining worker " \
        "would admit new attaches)"
    remove = funcs["TPUMountService.remove_tpu"]
    assert any(fn.attr == "inflight" for fn in _called_attrs(remove)), \
        "remove_tpu no longer holds an in-flight token (drain could " \
        "not settle on it)"


def test_grpc_adapter_maps_draining_before_generic_errors():
    tree = _parse("worker/grpc_server.py")
    handler = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "handle":
            src = ast.dump(node)
            if "WorkerDrainingError" in src:
                handler = node
                break
    assert handler is not None, \
        "the AddTPU gRPC handler no longer catches WorkerDrainingError " \
        "— a drain refusal would surface as INTERNAL instead of the " \
        "typed draining UNAVAILABLE"
