"""Mount façade + namespace actuation tests (ref analog: none — util.go had no
tests; scenarios from SURVEY.md §3.2/3.3 call stacks)."""

import os

import pytest

from gpumounter_tpu.actuation.cgroup import CgroupDeviceController
from gpumounter_tpu.actuation.mount import TPUMounter, can_mount
from gpumounter_tpu.actuation.nsenter import (ProcRootActuator,
                                              RecordingActuator)
from gpumounter_tpu.device.enumerator import PyEnumerator
from gpumounter_tpu.device.fake import FakeEnumerator, make_chips
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.errors import (ActuationError, DeviceBusyError)
from tests.test_cgroup import UID, mk_pod


# -- policy (ref util.go:207-226) ----------------------------------------------

def test_can_mount_matrix():
    MT = consts.MountType
    assert can_mount(MT.NONE, False)
    assert can_mount(MT.NONE, True)
    assert can_mount(MT.SINGLE, False)
    assert not can_mount(MT.SINGLE, True)
    assert not can_mount(MT.ENTIRE, False)
    assert not can_mount(MT.ENTIRE, True)
    assert not can_mount(MT.UNKNOWN, False)
    assert not can_mount(MT.UNKNOWN, True)


# -- fixtures ------------------------------------------------------------------

@pytest.fixture
def rig(fake_host):
    """Container cgroup + live pid + fake chips, wired through real
    CgroupDeviceController(v1) and RecordingActuator."""
    pod = mk_pod(qos_reported="Guaranteed")
    cid = "containerd://" + "ab" * 32
    ctrl = CgroupDeviceController(fake_host, driver="cgroupfs", version=1)
    cdir = ctrl.container_dir(pod, cid)
    os.makedirs(cdir)
    with open(os.path.join(cdir, "cgroup.procs"), "w") as f:
        f.write("4242\n4243\n")
    os.makedirs(os.path.join(fake_host.proc_root, "4242"))
    enum = FakeEnumerator(make_chips(4))
    actuator = RecordingActuator()
    mounter = TPUMounter(ctrl, actuator, enum, fake_host)
    return pod, mounter, actuator, enum, cdir


def test_mount_chips_full_path(rig):
    pod, mounter, actuator, enum, cdir = rig
    chips = make_chips(2)
    mounter.mount_chips(pod, chips, chips)
    # cgroup v1 allows written for both chips
    assert open(os.path.join(cdir, "devices.allow")).read().splitlines() \
        == ["c 120:0 rw", "c 120:1 rw"]
    # device nodes created via the first LIVE pid (4242; 4243 has no /proc dir)
    assert actuator.created == [(4242, "/dev/accel0", 120, 0),
                                (4242, "/dev/accel1", 120, 1)]


def test_mount_no_containers_raises(rig):
    pod, mounter, *_ = rig
    pod["status"]["containerStatuses"] = []
    with pytest.raises(ActuationError):
        mounter.mount_chips(pod, make_chips(1), make_chips(1))


def test_mount_no_live_pid_raises(rig, fake_host):
    pod, mounter, actuator, enum, cdir = rig
    os.rmdir(os.path.join(fake_host.proc_root, "4242"))
    with pytest.raises(ActuationError):
        mounter.mount_chips(pod, make_chips(1), make_chips(1))


def test_unmount_clean(rig):
    pod, mounter, actuator, enum, cdir = rig
    chips = make_chips(2)
    mounter.mount_chips(pod, chips, chips)
    mounter.unmount_chips(pod, [chips[0]], [chips[1]])
    assert open(os.path.join(cdir, "devices.deny")).read().splitlines() \
        == ["c 120:0 rw"]
    assert actuator.removed == [(4242, "/dev/accel0")]
    assert actuator.killed == []


def test_unmount_busy_raises_with_pids(rig):
    pod, mounter, actuator, enum, cdir = rig
    chips = make_chips(1)
    enum.busy_pids = {"/dev/accel0": [4242]}
    with pytest.raises(DeviceBusyError) as exc:
        mounter.unmount_chips(pod, chips, [])
    assert exc.value.pids == [4242]
    assert actuator.removed == []  # nothing touched on busy


def test_unmount_force_kills_holders(rig):
    pod, mounter, actuator, enum, cdir = rig
    chips = make_chips(1)
    enum.busy_pids = {"/dev/accel0": [4242]}
    mounter.unmount_chips(pod, chips, [], force=True)
    assert actuator.removed == [(4242, "/dev/accel0")]
    assert actuator.killed == [(4242, 9)]


def test_pod_device_processes_intersection(rig):
    pod, mounter, actuator, enum, cdir = rig
    # 9999 holds the device but is NOT in the container cgroup
    enum.busy_pids = {"/dev/accel0": [4242, 9999]}
    assert mounter.pod_device_processes(pod, make_chips(1)[0]) == [4242]


# -- fused batch actuation (one namespace crossing per container) --------------

def test_mount_is_one_batch_per_container(rig):
    """Chips + companions fuse into a single apply_device_nodes call —
    the entire-node attach pays ONE crossing, not one per node."""
    from gpumounter_tpu.device.model import CompanionNode
    pod, mounter, actuator, enum, cdir = rig
    chips = make_chips(2)
    vfio = CompanionNode(host_path="/dev/vfio/vfio", major=10, minor=196)
    for chip in chips:
        chip.companions = (vfio,)
    mounter.mount_chips(pod, chips, chips)
    assert len(actuator.batches) == 1
    pid, created_paths, removed_paths = actuator.batches[0]
    # shared companion deduplicated: one node per container, not per chip
    assert created_paths == ("/dev/accel0", "/dev/vfio/vfio", "/dev/accel1")
    assert removed_paths == ()


def test_unmount_is_one_batch_per_container(rig):
    pod, mounter, actuator, enum, cdir = rig
    chips = make_chips(2)
    mounter.mount_chips(pod, chips, chips)
    actuator.batches.clear()
    mounter.unmount_chips(pod, chips, [])
    assert len(actuator.batches) == 1
    assert actuator.batches[0][2] == ("/dev/accel0", "/dev/accel1")


def test_batch_metrics_recorded(rig):
    from gpumounter_tpu.utils.metrics import REGISTRY
    pod, mounter, actuator, enum, cdir = rig
    batches = REGISTRY.actuation_batches.value(op="create")
    ops = REGISTRY.actuation_batch_ops.value(op="create")
    chips = make_chips(3)
    mounter.mount_chips(pod, chips, chips)
    assert REGISTRY.actuation_batches.value(op="create") == batches + 1
    assert REGISTRY.actuation_batch_ops.value(op="create") == ops + 3
    assert REGISTRY.actuation_batch_size.value(op="create") == 3


class _ScriptingNsenter:
    """Capture seam for NsenterActuator's shell scripts."""

    def __init__(self, stdout=""):
        from gpumounter_tpu.actuation.nsenter import NsenterActuator
        self.inner = NsenterActuator()
        self.scripts = []
        self.stdout = stdout
        self.inner._run_in_mount_ns = self._capture

    def _capture(self, pid, script):
        self.scripts.append((pid, script))
        return self.stdout


def test_nsenter_batch_is_one_shell_invocation():
    """The fused path spawns nsenter ONCE for the whole batch; the script
    is idempotent per node and fails fast on the first real error."""
    cap = _ScriptingNsenter(stdout="created\ncreated\n")
    made = cap.inner.apply_device_nodes(
        4242,
        creates=[("/dev/accel0", 120, 0), ("/dev/accel1", 120, 1)],
        removes=["/dev/accel9"])
    assert made == 2
    assert len(cap.scripts) == 1
    pid, script = cap.scripts[0]
    assert pid == 4242
    assert script.startswith("set -e")
    assert script.count("mknod") == 2
    assert script.count("test -e") == 2          # idempotent short-circuit
    assert "rm -f /dev/accel9" in script
    # empty batch: no crossing at all
    assert cap.inner.apply_device_nodes(4242) == 0
    assert len(cap.scripts) == 1


def test_multi_container_batches_fan_out(fake_host):
    """Two containers => two batches (one crossing each), regardless of
    chip count."""
    from tests.helpers import WorkerRig
    from tests.test_multicontainer import make_two_container_pod
    rig = WorkerRig(fake_host, n_chips=4)
    try:
        pod = make_two_container_pod()
        rig.sim.kube.put_pod(pod)
        rig.provision_container(pod)
        outcome = rig.service.add_tpu(pod["metadata"]["name"], "default",
                                      4, True)
        assert outcome.result.name == "SUCCESS"
        create_batches = [b for b in rig.actuator.batches if b[1]]
        assert len(create_batches) == 2          # one per container
        for _, created_paths, _ in create_batches:
            assert len(created_paths) == 4
    finally:
        rig.close()


# -- ProcRootActuator end-to-end on a fixture tree -----------------------------

def test_proc_root_actuator_fake_nodes(fake_host):
    actuator = ProcRootActuator(fake_host, fake_nodes=True)
    container_root = os.path.join(fake_host.proc_root, "4242", "root")
    os.makedirs(os.path.join(container_root, "dev"))
    actuator.create_device_node(4242, "/dev/accel0", 120, 0)
    node = os.path.join(container_root, "dev", "accel0")
    assert os.path.exists(node)
    assert open(node + ".majmin").read() == "120:0"
    # the created node is visible to an enumerator scanning the container's /dev
    from gpumounter_tpu.utils.config import HostPaths
    inner = PyEnumerator(HostPaths(dev_root=os.path.join(container_root, "dev")),
                         allow_fake=True)
    assert [c.minor for c in inner.enumerate()] == [0]
    # idempotent create
    actuator.create_device_node(4242, "/dev/accel0", 120, 0)
    actuator.remove_device_node(4242, "/dev/accel0")
    assert not os.path.exists(node)
    assert not os.path.exists(node + ".majmin")


def test_proc_root_actuator_real_mknod_if_privileged(fake_host):
    actuator = ProcRootActuator(fake_host, fake_nodes=False)
    os.makedirs(os.path.join(fake_host.proc_root, "1", "root", "dev"))
    try:
        actuator.create_device_node(1, "/dev/accel0", 120, 0)
    except ActuationError:
        pytest.skip("no CAP_MKNOD in this environment")
    import stat
    st = os.stat(os.path.join(fake_host.proc_root, "1", "root", "dev",
                              "accel0"))
    assert stat.S_ISCHR(st.st_mode)
    assert os.major(st.st_rdev) == 120 and os.minor(st.st_rdev) == 0
    assert stat.S_IMODE(st.st_mode) == consts.DEVICE_FILE_MODE


def test_kill_processes_tolerates_gone_pids(fake_host):
    ProcRootActuator(fake_host).kill_processes([2 ** 22 + 12345])  # no raise
