"""Elastic mesh reshaping (jaxcheck/elastic.py + POST /slice/resize):
the worker's mesh-generation notification file, the harness's drain →
rebuild → restore-resharded sequence, and the acceptance e2e — a live
training loop rides a slice resize 2→4 hosts (and back) on the CPU sim
stack with its loss trajectory intact (no reset).

The step factory used here runs FULL attention under sharding hints
(the ring/shard_map kernels need a newer jax than some environments
carry); the harness itself is attention-agnostic — production passes
the flagship ring step.
"""

import json
import urllib.request

import numpy as np
import pytest

from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import HostPaths

jax = pytest.importorskip("jax")

from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from gpumounter_tpu.jaxcheck import elastic  # noqa: E402
from gpumounter_tpu.jaxcheck import model as model_lib  # noqa: E402
from gpumounter_tpu.jaxcheck import train as train_lib  # noqa: E402
from gpumounter_tpu.jaxcheck.ring_attention import full_attention  # noqa: E402

TINY = model_lib.ModelConfig(vocab=64, d_model=32, n_heads=4, n_layers=1,
                             d_ff=64)


def full_attn_step_factory(cfg, mesh, optimizer):
    """Sharded train step with full attention: tokens ride (data, seq),
    params carry the Megatron specs, XLA lays the collectives — the
    shard_map-free stand-in for the ring step."""
    import optax

    def loss_fn(params, tokens):
        logits = model_lib.forward(params, tokens, cfg,
                                   attn_fn=full_attention)
        return train_lib.cross_entropy(logits, tokens)

    def step(state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return train_lib.TrainState(params, opt_state, state.step + 1), \
            loss

    return jax.jit(step, donate_argnums=0,
                   in_shardings=(None, NamedSharding(mesh,
                                                     P("data", "seq"))))


def _batch(i, batch=4, seq=16):
    key = jax.random.fold_in(jax.random.PRNGKey(7), i)
    return np.asarray(train_lib.make_batch(key, batch, seq, TINY.vocab))


# -- worker-side notification file ---------------------------------------------

def test_worker_stamps_mesh_generation_file_on_actuation(fake_host,
                                                         tmp_path):
    from gpumounter_tpu.testing.sim import WorkerRig
    rig = WorkerRig(fake_host, n_chips=4)
    try:
        gen_dir = tmp_path / "mesh-gen"
        rig.sim.settings.mesh_gen_dir = str(gen_dir)
        outcome = rig.service.add_tpu("workload", "default", 4, True,
                                      request_id="rid-gen")
        assert outcome.result == consts.AddResult.SUCCESS
        path = gen_dir / "default--workload.json"
        payload = elastic.read_generation_file(str(path))
        assert payload is not None
        assert len(payload["chips"]) == 4
        first = payload["generation"]
        assert first > 0
        signal = elastic.FileSignal(str(path))
        assert signal.chips() == 4
        assert signal.generation() == first

        outcome = rig.service.remove_tpu("workload", "default", [], False,
                                         request_id="rid-gen-2")
        assert outcome.result == consts.RemoveResult.SUCCESS
        payload = elastic.read_generation_file(str(path))
        assert payload["chips"] == []
        assert payload["generation"] > first
    finally:
        rig.close()


def test_generation_file_disabled_by_default(fake_host):
    from gpumounter_tpu.testing.sim import WorkerRig
    rig = WorkerRig(fake_host, n_chips=4)
    try:
        assert rig.sim.settings.mesh_gen_dir == ""
        outcome = rig.service.add_tpu("workload", "default", 4, True)
        assert outcome.result == consts.AddResult.SUCCESS
    finally:
        rig.close()


# -- harness: drain → rebuild → restore resharded ------------------------------

def test_harness_reshapes_without_resetting_the_trajectory():
    signal = {"gen": 1, "chips": 4}
    harness = elastic.ElasticHarness(
        TINY, lambda: signal["gen"], lambda: signal["chips"],
        optimizer=train_lib.make_optimizer(lr=1e-2),
        step_factory=full_attn_step_factory).start()
    try:
        assert harness.mesh.devices.shape == (1, 4, 1)
        losses = [harness.train_step(_batch(i)) for i in range(12)]
        embed_before = np.asarray(
            jax.device_get(harness.state.params["embed"]))
        step_before = int(harness.state.step)

        # grow 4 -> 8 devices
        signal.update(gen=2, chips=8)
        assert harness.poll() is True
        assert harness.mesh.devices.shape == (1, 8, 1)
        # NO reset: the restored parameters are bit-for-bit the drained
        # ones, just resharded — and the step counter keeps counting
        embed_after = np.asarray(
            jax.device_get(harness.state.params["embed"]))
        np.testing.assert_array_equal(embed_before, embed_after)
        assert int(harness.state.step) == step_before == 12
        losses += [harness.train_step(_batch(i)) for i in range(12, 24)]
        assert int(harness.state.step) == 24
        assert harness.poll() is False      # no bump, no reshape

        # shrink 8 -> 4 devices, same contract
        signal.update(gen=3, chips=4)
        assert harness.poll() is True
        assert harness.mesh.devices.shape == (1, 4, 1)
        assert int(harness.state.step) == 24
        losses += [harness.train_step(_batch(i)) for i in range(24, 36)]
        # the trajectory went DOWN across both reshapes (training data is
        # learnable arithmetic sequences; lr tuned for fast descent)
        assert np.mean(losses[-6:]) < np.mean(losses[:6]), losses
        assert harness.reshapes == 2
    finally:
        harness.close()


def test_harness_refuses_impossible_chip_count():
    signal = {"gen": 1, "chips": 10_000}
    harness = elastic.ElasticHarness(
        TINY, lambda: signal["gen"], lambda: signal["chips"],
        step_factory=full_attn_step_factory)
    with pytest.raises(RuntimeError, match="attach/visibility mismatch"):
        harness.start()


# -- acceptance e2e: resize a live slice under a training loop -----------------

def _host(tmp_path, i):
    base = tmp_path / f"node{i}"
    for sub in ("dev", "proc", "sys/fs/cgroup"):
        (base / sub).mkdir(parents=True)
    return HostPaths(dev_root=str(base / "dev"),
                     proc_root=str(base / "proc"),
                     sys_root=str(base / "sys"),
                     cgroup_root=str(base / "sys" / "fs" / "cgroup"),
                     kubelet_socket=str(base / "pr" / "kubelet.sock"))


def _post(url, obj):
    req = urllib.request.Request(url, data=json.dumps(obj).encode(),
                                 method="POST")
    try:
        resp = urllib.request.urlopen(req, timeout=60)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _target(n):
    return {"pods": [{"namespace": "default", "pod": f"workload-{i}"}
                     for i in range(n)], "tpusPerHost": 2}


def test_training_loop_rides_slice_resize_end_to_end(tmp_path):
    """The acceptance flow: a jaxcheck training loop over an attached
    2-host slice drains, the control plane resizes the slice 2→4 hosts
    via POST /slice/resize, the loop restores resharded onto the larger
    mesh and keeps descending — then shrinks back 4→2 likewise. Chips
    map to virtual CPU devices (2/host × 4 hosts = the suite's 8-device
    pin); the generation signal is the master's /slicez view."""
    from gpumounter_tpu.testing.sim import MultiNodeStack
    stack = MultiNodeStack([_host(tmp_path, i) for i in range(4)],
                           n_chips=2)
    harness = None
    try:
        status, body = _post(f"{stack.base}/addtpuslice", _target(2))
        assert status == 200, body
        group = body["group"]
        signal = elastic.MasterSliceSignal(stack.base, group)
        assert signal.generation() == 1
        assert signal.chips() == 4

        harness = elastic.ElasticHarness(
            TINY, signal.generation, signal.chips,
            optimizer=train_lib.make_optimizer(lr=1e-2),
            step_factory=full_attn_step_factory).start()
        assert harness.mesh.devices.shape == (1, 4, 1)
        losses = []
        for i in range(10):
            harness.poll()
            losses.append(harness.train_step(_batch(i)))

        # GROW: the control plane reshapes the slice 2 -> 4 hosts
        status, body = _post(f"{stack.base}/slice/resize", _target(4))
        assert status == 200, body
        assert body["generation"] == 2
        embed_before = np.asarray(
            jax.device_get(harness.state.params["embed"]))
        assert harness.poll() is True       # generation bump observed
        assert harness.mesh.devices.shape == (1, 8, 1)
        np.testing.assert_array_equal(
            embed_before,
            np.asarray(jax.device_get(harness.state.params["embed"])))
        assert int(harness.state.step) == 10      # trajectory continues
        for i in range(10, 20):
            harness.poll()
            losses.append(harness.train_step(_batch(i)))
        assert int(harness.state.step) == 20

        # SHRINK: 4 -> 2 hosts, loop keeps going on the smaller mesh
        status, body = _post(f"{stack.base}/slice/resize", _target(2))
        assert status == 200, body
        assert body["generation"] == 3
        assert harness.poll() is True
        assert harness.mesh.devices.shape == (1, 4, 1)
        for i in range(20, 30):
            harness.poll()
            losses.append(harness.train_step(_batch(i)))
        assert int(harness.state.step) == 30
        assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses
        assert harness.reshapes == 2
        # ground truth followed the resizes: only hosts 0-1 hold chips
        for i, rig in enumerate(stack.rigs):
            assert len(rig.sim.slave_pods()) == (1 if i < 2 else 0)
    finally:
        if harness is not None:
            harness.close()
        stack.close()
