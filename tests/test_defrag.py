"""Safe fleet defragmenter (ISSUE 18).

Unit coverage for the actuator's interlocks (hysteresis counted on real
fleet ticks, idle-only, cordoned-source exclusion, duty/busy-chip
refusal, the in-flight cap, the sliding budget and its halt transition,
the post-move score check charging thrash) and the failover adoption
decision table; then the acceptance e2es — an act-mode 4-host fragmented
fleet consolidates through the repair seam (score strictly drops, the
freed host is schedulable again, busy gangs never move), a master
SIGKILL'd mid-move leaves the group at exactly the old or the new
placement depending on whether the adopted grow could complete,
plan mode journals and reports but never actuates, and
TPU_DEFRAG_MODE=0 removes the actuator and its /fleetz section.
"""

from __future__ import annotations

import json
import threading
import time
import types
import urllib.request

import pytest

from gpumounter_tpu.master import defrag as defrag_mod
from gpumounter_tpu.master.admission import BrokerConfig
from gpumounter_tpu.master.defrag import DefragActuator
from gpumounter_tpu.master.store import DefragMoveRecord
from gpumounter_tpu.testing.chaos import (assert_defrag_invariants,
                                          assert_slice_invariants)
from gpumounter_tpu.testing.sim import (MultiMasterStack, MultiNodeStack,
                                        WorkerRig)
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.config import HostPaths
from gpumounter_tpu.utils.metrics import REGISTRY

NS = consts.DEFAULT_POOL_NAMESPACE


def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _run_cli(base, *argv):
    import contextlib
    import io

    from gpumounter_tpu import cli
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli.main(["--master", base, *argv])
    return rc, out.getvalue()


def _host(tmp_path, i):
    base = tmp_path / f"node{i}"
    for sub in ("dev", "proc", "sys/fs/cgroup"):
        (base / sub).mkdir(parents=True)
    return HostPaths(dev_root=str(base / "dev"),
                     proc_root=str(base / "proc"),
                     sys_root=str(base / "sys"),
                     cgroup_root=str(base / "sys" / "fs" / "cgroup"),
                     kubelet_socket=str(base / "pr" / "kubelet.sock"))


def _wait(predicate, timeout_s=20.0, message=""):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(message or "condition never held")


# -- unit rig: a fake repair seam + a scripted topology view -------------------

class _Slices:
    """The repair seam, scripted: records every migrate_member call and
    answers with a canned result; group membership is a plain dict."""

    def __init__(self):
        self.members: dict[str, list] = {}
        self.calls: list[tuple] = []
        self.result: dict = {"outcome": "migrated", "generation": 2,
                             "added": [("default", "spare-0")]}
        self.inflight_rids: set[str] = set()
        self.finished: list[tuple] = []
        self.finish_ok = True
        self.broker = types.SimpleNamespace(
            leases=types.SimpleNamespace(
                group_leases=lambda g: list(self.members.get(g, []))),
            _on_fenced=lambda e: None)

    def migrate_member(self, group, member, rid):
        self.calls.append((group, tuple(member), rid))
        return dict(self.result)

    def txn_inflight(self, rid):
        return rid in self.inflight_rids

    def finish_member_detach(self, group, member, rid):
        self.finished.append((group, tuple(member), rid))
        return self.finish_ok


class _ViewBox:
    """A hand-cranked FleetTopology.snapshot(): tests advance the tick
    counter explicitly — the actuator must count THESE, not its own
    wakeups."""

    def __init__(self):
        self.ticks = 0
        self.score = 0.7
        self.cands: list[dict] = []

    def snapshot(self):
        return {"enabled": True, "ticks": self.ticks,
                "fleet": {"score": self.score, "nodes": {"node-0": {}},
                          "defrag_candidates": [dict(c)
                                                for c in self.cands]}}


def _cand(**kw):
    base = {"namespace": "default", "pod": "w0", "tenant": "t",
            "node": "node-0", "chips": 1, "gain": 2, "idle": True,
            "group": "g1"}
    base.update(kw)
    return base


def _lease(ns="default", pod="w0"):
    return types.SimpleNamespace(namespace=ns, pod=pod)


def _actuator(sl, box, **kw):
    kw.setdefault("mode", "act")
    kw.setdefault("hysteresis_ticks", 3)
    kw.setdefault("max_inflight", 1)
    kw.setdefault("budget", 4)
    return DefragActuator(slices=sl, view_fn=box.snapshot, **kw)


def _round(box, act):
    box.ticks += 1
    act.tick()


def test_hysteresis_counts_fleet_ticks_not_wakeups():
    sl, box = _Slices(), _ViewBox()
    sl.members["g1"] = [_lease()]
    box.cands = [_cand()]
    act = _actuator(sl, box, hysteresis_ticks=3)
    for _ in range(2):
        _round(box, act)
    # extra wakeups against an UNCHANGED fleet tick must not advance
    # the streak — the whole point of gating on the view's counter
    for _ in range(5):
        act.tick()
    assert sl.calls == []
    _round(box, act)                       # 3rd real fleet tick
    assert len(sl.calls) == 1
    assert sl.calls[0][:2] == ("g1", ("default", "w0"))


def test_candidate_vanishing_resets_the_streak():
    sl, box = _Slices(), _ViewBox()
    sl.members["g1"] = [_lease()]
    box.cands = [_cand()]
    act = _actuator(sl, box, hysteresis_ticks=3)
    _round(box, act)
    _round(box, act)
    box.cands = []                         # gone for one tick
    _round(box, act)
    box.cands = [_cand()]
    _round(box, act)                       # streak restarts at 1
    _round(box, act)
    assert sl.calls == []
    _round(box, act)
    assert len(sl.calls) == 1


@pytest.mark.parametrize("why,cand_kw,act_kw", [
    ("not idle", {"idle": False}, {}),
    ("not a group lease", {"group": ""}, {}),
    ("cordoned source", {}, {"node_excluded_fn": lambda node: True}),
    ("duty above threshold", {},
     {"activity_fn": lambda: {("default", "w0"): {"duty": 0.5,
                                                  "busy_chips": 0}}}),
    ("busy chips", {},
     {"activity_fn": lambda: {("default", "w0"): {"duty": 0.0,
                                                  "busy_chips": 1}}}),
])
def test_interlocks_never_issue_the_move(why, cand_kw, act_kw):
    sl, box = _Slices(), _ViewBox()
    sl.members["g1"] = [_lease()]
    box.cands = [_cand(**cand_kw)]
    act = _actuator(sl, box, hysteresis_ticks=1, **act_kw)
    for _ in range(5):
        _round(box, act)
    assert sl.calls == [], why
    assert act.fleetz_section()["plans"] == [], why


def test_repair_in_flight_defers_and_keeps_the_group():
    """The per-group guard is SHARED with repair_group: the seam answers
    "repair in flight" and the actuator records a deferral — nothing
    retried in the same pass, nothing torn down."""
    sl, box = _Slices(), _ViewBox()
    sl.members["g1"] = [_lease()]
    sl.result = {"outcome": "deferred", "why": "repair in flight"}
    box.cands = [_cand()]
    act = _actuator(sl, box, hysteresis_ticks=1)
    _round(box, act)
    assert len(sl.calls) == 1
    recent = act.fleetz_section()["recent"]
    assert recent[0]["outcome"] == "deferred"
    assert recent[0]["why"] == "repair in flight"
    assert sl.members["g1"]                 # group untouched


def test_budget_exhaustion_halts_until_the_window_slides():
    sl, box = _Slices(), _ViewBox()
    sl.members["g1"] = [_lease()]
    box.cands = [_cand()]
    base = REGISTRY.defrag_moves.value(outcome="budget_exhausted")
    act = _actuator(sl, box, hysteresis_ticks=1, budget=2)
    _round(box, act)
    box.score = 0.6          # each move improves the score: the only
    _round(box, act)         # budget charges are the moves themselves
    assert len(sl.calls) == 2
    # third and fourth pass: budget spent — halted, ONE transition note
    box.score = 0.5
    _round(box, act)
    _round(box, act)
    assert len(sl.calls) == 2
    assert REGISTRY.defrag_moves.value(outcome="budget_exhausted") \
        == base + 1
    assert act.fleetz_section()["budget"]["exhausted"] is True
    # the window slides: stamps age out, the actuator resumes
    act._move_stamps[:] = [time.monotonic()
                           - consts.DEFRAG_BUDGET_WINDOW_S - 1.0] * 2
    _round(box, act)
    assert len(sl.calls) == 3
    assert act.fleetz_section()["budget"]["exhausted"] is False


def test_failed_score_check_charges_budget_and_rearms_hysteresis():
    sl, box = _Slices(), _ViewBox()
    sl.members["g1"] = [_lease()]
    box.cands = [_cand()]
    act = _actuator(sl, box, hysteresis_ticks=2, budget=10)
    _round(box, act)
    _round(box, act)                       # streak 2 -> move
    assert len(sl.calls) == 1
    # the fleet score never improves: the NEXT tick's verify pass
    # charges the budget and clears the group's streak
    _round(box, act)
    assert len(act._move_stamps) == 2      # the move + the charge
    assert sl.calls and len(sl.calls) == 1
    recent = act.fleetz_section()["recent"]
    assert recent[0]["outcome"] == "migrated"
    assert recent[0]["improved"] is False
    # hysteresis re-armed: one more tick is not enough again
    _round(box, act)
    assert len(sl.calls) == 2


def test_improved_score_does_not_charge_the_budget():
    sl, box = _Slices(), _ViewBox()
    sl.members["g1"] = [_lease()]
    box.cands = [_cand()]
    act = _actuator(sl, box, hysteresis_ticks=1, budget=10)
    _round(box, act)
    assert len(sl.calls) == 1
    box.score = 0.4                        # the move worked
    box.cands = []
    _round(box, act)
    assert len(act._move_stamps) == 1      # the move only, no charge
    assert act.fleetz_section()["recent"][0]["improved"] is True


def test_plan_mode_journals_and_reports_but_never_actuates():
    sl, box = _Slices(), _ViewBox()
    sl.members["g1"] = [_lease()]
    box.cands = [_cand()]
    base = REGISTRY.defrag_moves.value(outcome="planned")
    act = _actuator(sl, box, mode="plan", hysteresis_ticks=1)
    for _ in range(4):
        _round(box, act)
    assert sl.calls == []
    section = act.fleetz_section()
    assert section["mode"] == "plan"
    assert [p["pod"] for p in section["plans"]] == ["w0"]
    assert REGISTRY.defrag_moves.value(outcome="planned") == base + 1


# -- failover adoption: the decision table -------------------------------------

class _Store:
    def __init__(self):
        self.put: list = []
        self.deleted: list = []

    def put_defrag_move(self, record):
        self.put.append(record)

    def delete_defrag_move(self, namespace, group, pod):
        self.deleted.append((namespace, group, pod))


def _record(**kw):
    base = dict(group="g1", namespace="default", pod="w0", rid="r1",
                hosts=1, src_node="node-0", state="acting")
    base.update(kw)
    return DefragMoveRecord(**base)


def _adopt_one(sl, record):
    store = _Store()
    act = DefragActuator(slices=sl, view_fn=lambda: None, store=store)
    assert act.adopt([record]) == (1 if record.state == "acting" else 0)
    act.join_adoptions()
    return act, store


def test_adopt_planned_record_drops_quietly():
    sl = _Slices()
    act, store = _adopt_one(sl, _record(state="planned"))
    assert store.deleted == [("default", "g1", "w0")]
    assert sl.finished == []


def test_adopt_group_gone_aborts():
    sl = _Slices()                          # no members at all
    base = REGISTRY.defrag_moves.value(outcome="aborted")
    act, store = _adopt_one(sl, _record())
    assert store.deleted == [("default", "g1", "w0")]
    assert REGISTRY.defrag_moves.value(outcome="aborted") == base + 1


def test_adopt_completed_move_is_migrated():
    sl = _Slices()
    sl.members["g1"] = [_lease(pod="spare-0")]      # old member gone
    base = REGISTRY.defrag_moves.value(outcome="migrated")
    act, store = _adopt_one(sl, _record())
    assert sl.finished == []                # nothing left to detach
    assert store.deleted == [("default", "g1", "w0")]
    assert REGISTRY.defrag_moves.value(outcome="migrated") == base + 1


def test_adopt_landed_grow_finishes_the_detach():
    sl = _Slices()
    sl.members["g1"] = [_lease(), _lease(pod="spare-0")]
    act, store = _adopt_one(sl, _record())
    assert sl.finished == [("g1", ("default", "w0"), "r1")]
    assert store.deleted == [("default", "g1", "w0")]


def test_adopt_unlanded_grow_aborts_to_old_placement():
    sl = _Slices()
    sl.members["g1"] = [_lease()]           # exactly the old world
    base = REGISTRY.defrag_moves.value(outcome="aborted")
    act, store = _adopt_one(sl, _record())
    assert sl.finished == []
    assert store.deleted == [("default", "g1", "w0")]
    assert REGISTRY.defrag_moves.value(outcome="aborted") == base + 1


def test_adopt_waits_for_the_inflight_slice_txn():
    sl = _Slices()
    sl.members["g1"] = [_lease(), _lease(pod="spare-0")]
    sl.inflight_rids.add("r1")
    store = _Store()
    act = DefragActuator(slices=sl, view_fn=lambda: None, store=store)
    act.adopt([_record()])
    time.sleep(0.2)
    assert sl.finished == []                # still polling
    sl.inflight_rids.discard("r1")
    act.join_adoptions()
    assert sl.finished == [("g1", ("default", "w0"), "r1")]


# -- acceptance e2e: consolidation through the repair seam ---------------------

def test_e2e_act_mode_consolidates_the_fragmented_fleet(tmp_path,
                                                        monkeypatch):
    """The PR's acceptance bar: a 4-host fleet fragmented by one idle
    1-chip group and three busy 2-chip gangs. In act mode the actuator
    waits out hysteresis, then migrates ONLY the idle group onto the
    spare host through the repair seam — the fleet score strictly
    drops, the freed host schedules a full 4-chip mount again, and the
    busy gangs never move."""
    monkeypatch.setenv(consts.ENV_DEFRAG_MODE, "act")
    stack = MultiNodeStack([_host(tmp_path, i) for i in range(4)],
                           n_chips=4, health=True, topo=True,
                           broker_config=BrokerConfig())
    base_migrated = REGISTRY.defrag_moves.value(outcome="migrated")
    try:
        defrag = stack.gateway.defrag
        assert defrag is not None and defrag.mode == "act"
        defrag.stop()                      # drive ticks by hand
        groups = stack.fragment([1, 2, 2, 2], idle=(0,))
        stack.add_workload(3, "spare-0", spare=True)
        busy_before = {
            i: (lease.node, lease.chips)
            for i in (1, 2, 3)
            for lease in [stack.gateway.broker.leases.get(
                "default", f"workload-{i}")]}

        stack.gateway.fleet.tick()
        before = _get_json(f"{stack.base}/fleetz")
        pre_score = before["topology"]["score"]
        assert pre_score == pytest.approx(1 - 2 / 9, abs=1e-3)
        # the plan set is visible on /fleetz before anything moves
        defrag.tick()
        assert before["defrag"]["mode"] == "act"

        for _ in range(6):
            if REGISTRY.defrag_moves.value(outcome="migrated") \
                    > base_migrated:
                break
            stack.gateway.fleet.tick()
            defrag.tick()
        assert REGISTRY.defrag_moves.value(outcome="migrated") \
            == base_migrated + 1

        # the idle group now lives on the spare host; the old member
        # detached cleanly (no slave pod left on node-0)
        members = stack.gateway.broker.leases.group_leases(groups[0])
        assert [(m.pod, m.node) for m in members] == \
            [("spare-0", "node-3")]
        assert stack.rigs[0].sim.slave_pods() == []
        assert stack.gateway.broker.leases.get(
            "default", "workload-0") is None
        # busy gangs never moved
        for i, (node, chips) in busy_before.items():
            lease = stack.gateway.broker.leases.get(
                "default", f"workload-{i}")
            assert (lease.node, lease.chips) == (node, chips), i

        # score strictly drops and node-0 merged whole
        stack.gateway.fleet.tick()
        defrag.tick()                      # the verify pass (improved)
        after = _get_json(f"{stack.base}/fleetz")
        assert after["topology"]["score"] < pre_score
        assert after["topology"]["nodes"]["node-0"][
            "largest_free_block"] == 4
        recent = after["defrag"]["recent"]
        assert recent and recent[0]["outcome"] == "migrated"
        assert recent[0]["group"] == groups[0]
        # a successful move never charges the budget
        assert after["defrag"]["budget"]["used"] == 1

        # the freed host is schedulable again: a full-host mount lands
        body = _get_json(
            f"{stack.base}/addtpu/namespace/default/pod/workload-0"
            f"/tpu/4/isEntireMount/true", timeout=60)
        assert body["result"] == "SUCCESS", body

        assert_defrag_invariants(stack.gateway.broker,
                                 actuator=defrag)
        assert_slice_invariants(stack.gateway.broker,
                                [rig.sim for rig in stack.rigs])

        # tpumounterctl defrag renders the move ring and the budget;
        # exhausting the budget flips the exit code non-zero
        rc, out = _run_cli(stack.base, "defrag")
        assert rc == 0, out
        assert "mode act" in out and "MIGRATED" in out
        with defrag._lock:
            defrag._move_stamps = [time.monotonic()] * defrag.budget
            defrag._budget_exhausted = True
        rc, out = _run_cli(stack.base, "defrag")
        assert rc != 0, out
        assert "BUDGET EXHAUSTED" in out
    finally:
        stack.close()


# -- acceptance e2e: SIGKILL mid-move ------------------------------------------

class _MasterCrash(BaseException):
    """Simulated master death mid-move: a BaseException skips every
    Exception-typed cleanup on the way out — no rollback, no record
    retirement, exactly what SIGKILL leaves."""


def _store_defrag_records(kube) -> list[DefragMoveRecord]:
    from gpumounter_tpu.utils.errors import K8sApiError
    try:
        cm = kube.get_config_map(NS, f"{consts.STORE_CONFIGMAP_PREFIX}0")
    except K8sApiError:
        return []
    out = []
    for key, value in (cm["metadata"].get("annotations") or {}).items():
        if key.startswith(consts.STORE_DEFRAG_ANNOTATION_PREFIX):
            out.append(DefragMoveRecord.from_json(value))
    return out


def _crash_stack(tmp_path, monkeypatch, queue_timeout_s):
    monkeypatch.setenv(consts.ENV_DEFRAG_MODE, "act")
    rigs = [WorkerRig(_host(tmp_path, i), n_chips=4, node=f"node-{i}",
                      pod_name=f"workload-{i}") for i in range(2)]
    stack = MultiMasterStack(
        rigs=rigs, masters=2, shards=1,
        broker_config=BrokerConfig(queue_timeout_s=queue_timeout_s,
                                   tick_interval_s=0.1))
    stack.wait_converged()
    # the spare destination on node-1, visible to the masters AND
    # provisioned on its node's worker
    spare = stack.rigs[1].sim.add_target_pod(
        name="spare-0", uid="uid-spare-0",
        container_id="containerd://" + ("ab" * 32)[:64])
    spare["metadata"]["labels"][consts.SLICE_SPARE_LABEL_KEY] = \
        consts.SLICE_SPARE_LABEL_VALUE
    stack.rigs[1].sim.kube.put_pod(spare)
    stack.rigs[1].provision_container(spare)
    stack.kube.put_pod(spare)
    return stack


def _crash_leader_mid_move(stack):
    """Journal + start the move on the leader and SIGKILL it while the
    grow is in flight: the defrag record (state=acting) and the slice
    txn record survive on the store — the survivor's breadcrumbs.
    Returns (group, leader index)."""
    leader = stack.leader_for("default")
    gateway = stack.gateways[leader]
    status, payload = gateway.handle("POST", "/addtpuslice", json.dumps({
        "pods": [{"namespace": "default", "pod": "workload-0"}],
        "tpusPerHost": 4}).encode())
    assert status == 200 and payload["result"] == "SUCCESS", payload
    group = payload["group"]
    # freeze the doomed leader's maintenance loops: a live master would
    # self-heal its own crashed move — the record must be left for the
    # SURVIVOR
    gateway.broker.stop()
    gateway.defrag.stop()
    crashed = threading.Event()

    def before_host_attach(namespace, pod):
        if pod == "spare-0":
            crashed.set()
            raise _MasterCrash()

    gateway.slices.before_host_attach = before_host_attach
    plan = {"namespace": "default", "pod": "workload-0", "tenant": "",
            "node": "node-0", "chips": 4, "gain": 2, "group": group,
            "rid": "defrag-crash1", "created_unix": round(time.time(), 3)}
    key = ("default", "workload-0", "node-0", group)

    def run():
        try:
            gateway.defrag._execute(key, plan, 0.9, 1)
        except BaseException:   # noqa: BLE001 — the simulated SIGKILL
            pass

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    assert crashed.wait(timeout=30), "crash point never armed"
    thread.join(timeout=10)
    # the torn mid-state, asserted while the frozen leader still holds
    # the lock: one acting defrag record, the group still whole at the
    # OLD placement
    records = _store_defrag_records(stack.kube)
    assert [(r.group, r.pod, r.state, r.hosts) for r in records] == \
        [(group, "workload-0", "acting", 1)]
    assert len(stack.rigs[0].sim.slave_pods()) == 1
    assert stack.rigs[1].sim.slave_pods() == []
    stack.kill(leader)
    return group, leader


def _survivor(stack, dead):
    [i] = [i for i in stack.live() if i != dead]
    return stack.gateways[i]


def test_e2e_crash_mid_move_survivor_completes_to_new_placement(
        tmp_path, monkeypatch):
    """Queue deadline still open at failover ⇒ the survivor finishes the
    adopted grow txn under the original rid, then the defrag adoption
    finishes the detach: the group lands WHOLE at the new placement."""
    stack = _crash_stack(tmp_path, monkeypatch, queue_timeout_s=30)
    try:
        group, dead = _crash_leader_mid_move(stack)
        surv = _survivor(stack, dead)
        _wait(lambda: not _store_defrag_records(stack.kube),
              timeout_s=30, message="defrag record never resolved")
        surv.defrag.join_adoptions()
        _wait(lambda: [
            (m.pod, m.node) for m in
            surv.broker.leases.group_leases(group)] ==
            [("spare-0", "node-1")],
            timeout_s=30, message="group never reached new placement")
        assert len(stack.rigs[1].sim.slave_pods()) == 1
        _wait(lambda: stack.rigs[0].sim.slave_pods() == [],
              timeout_s=30, message="old member never detached")
        assert_slice_invariants(surv.broker,
                                [rig.sim for rig in stack.rigs],
                                store=surv.broker.store)
        assert_defrag_invariants(surv.broker, store=surv.broker.store,
                                 actuator=surv.defrag)
    finally:
        stack.close()


def test_e2e_crash_mid_move_survivor_aborts_to_old_placement(
        tmp_path, monkeypatch):
    """Queue deadline already passed at failover ⇒ the adopted grow txn
    rolls back, the defrag adoption sees the grow never landed and
    aborts: the group stays WHOLE at the old placement."""
    stack = _crash_stack(tmp_path, monkeypatch, queue_timeout_s=0)
    try:
        group, dead = _crash_leader_mid_move(stack)
        surv = _survivor(stack, dead)
        _wait(lambda: not _store_defrag_records(stack.kube),
              timeout_s=30, message="defrag record never resolved")
        surv.defrag.join_adoptions()
        members = surv.broker.leases.group_leases(group)
        assert [(m.pod, m.node) for m in members] == \
            [("workload-0", "node-0")]
        assert len(stack.rigs[0].sim.slave_pods()) == 1
        assert stack.rigs[1].sim.slave_pods() == []
        assert_slice_invariants(surv.broker,
                                [rig.sim for rig in stack.rigs],
                                store=surv.broker.store)
        assert_defrag_invariants(surv.broker, store=surv.broker.store,
                                 actuator=surv.defrag)
    finally:
        stack.close()


# -- plan mode + mode 0 --------------------------------------------------------

def test_e2e_plan_mode_reports_but_never_moves(tmp_path):
    """The staged-rollout default: plans appear on /fleetz and as
    defrag_plan events, but nothing is ever actuated — no slave pod
    moves, no migrated outcome, mode says plan."""
    stack = MultiNodeStack([_host(tmp_path, i) for i in range(2)],
                           n_chips=4, health=True, topo=True,
                           broker_config=BrokerConfig())
    base_migrated = REGISTRY.defrag_moves.value(outcome="migrated")
    try:
        defrag = stack.gateway.defrag
        assert defrag is not None and defrag.mode == "plan"
        defrag.stop()
        stack.fragment([1, 2], idle=(0,))
        stack.add_workload(1, "spare-0", spare=True)
        slaves_before = [len(rig.sim.slave_pods())
                         for rig in stack.rigs]
        for _ in range(5):
            stack.gateway.fleet.tick()
            defrag.tick()
        fleetz = _get_json(f"{stack.base}/fleetz")
        section = fleetz["defrag"]
        assert section["mode"] == "plan"
        assert [p["pod"] for p in section["plans"]] == ["workload-0"]
        eventz = _get_json(f"{stack.base}/eventz?limit=-1")
        assert any(e["kind"] == "defrag_plan"
                   and e.get("pod") == "workload-0"
                   for e in eventz["events"])
        assert REGISTRY.defrag_moves.value(outcome="migrated") \
            == base_migrated
        assert [len(rig.sim.slave_pods()) for rig in stack.rigs] \
            == slaves_before
        assert_defrag_invariants(stack.gateway.broker, actuator=defrag)
        # the CLI labels plan mode and lists the standing plan
        rc, out = _run_cli(stack.base, "defrag")
        assert rc == 0, out
        assert "mode plan" in out and "no moves" in out
        assert "move default/workload-0" in out
    finally:
        stack.close()


def test_e2e_mode_0_removes_the_actuator_and_section(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv(consts.ENV_DEFRAG_MODE, "0")
    stack = MultiNodeStack([_host(tmp_path, 0)], n_chips=4,
                           health=True, topo=True,
                           broker_config=BrokerConfig())
    try:
        assert stack.gateway.defrag is None
        stack.gateway.fleet.tick()
        fleetz = _get_json(f"{stack.base}/fleetz")
        assert "defrag" not in fleetz
        assert "topology" in fleetz        # the measurement half stays
        # the CLI reports the disabled defragmenter as a state, exit 0
        rc, out = _run_cli(stack.base, "defrag")
        assert rc == 0, out
        assert "disabled" in out
    finally:
        stack.close()
