"""HA control-plane suite (ISSUE 8 tentpole): multi-master forwarding
(proxy + 307 redirect + leaderless 503), single-master restart
rehydrating BOTH leases and parked waiters from the intent store, shard
hand-off waking waiters to re-route, the /fleetz master-role section —
and the acceptance chaos plan: kill the leading master with a non-empty
queue, the surviving replica assumes the shard, rehydrates the persisted
waiters and drains every one with zero double-actuation."""

import http.client
import json
import threading
import time
import urllib.parse

import pytest

from gpumounter_tpu.master.admission import AttachBroker, BrokerConfig
from gpumounter_tpu.master.discovery import WorkerDirectory
from gpumounter_tpu.master.election import NullElection
from gpumounter_tpu.master.gateway import MasterGateway
from gpumounter_tpu.master.shardring import HAConfig, ShardRing
from gpumounter_tpu.master.store import IntentStore
from gpumounter_tpu.testing.chaos import (assert_broker_invariants,
                                          assert_invariants,
                                          wait_events_drained)
from gpumounter_tpu.testing.sim import MultiMasterStack
from gpumounter_tpu.utils import consts

from tests.test_broker import BrokerStack
from tests.helpers import WorkerRig


def req(base, method, path, body=None, headers=None, timeout=30.0):
    """One raw round-trip (no redirect following): (status, headers,
    payload)."""
    parsed = urllib.parse.urlparse(base)
    conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                      timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            payload = json.loads(raw)
        except ValueError:
            payload = {"raw": raw.decode(errors="replace")}
        return resp.status, dict(resp.getheaders()), payload
    finally:
        conn.close()


def add_path(pod, n, entire=False, ns="default"):
    return (f"/addtpu/namespace/{ns}/pod/{pod}/tpu/{n}"
            f"/isEntireMount/{'true' if entire else 'false'}")


def remove_path(pod, force=False, ns="default"):
    return (f"/removetpu/namespace/{ns}/pod/{pod}"
            f"/force/{'true' if force else 'false'}")


@pytest.fixture
def mm_factory(fake_host):
    stacks = []

    def make(**kwargs) -> MultiMasterStack:
        rig = kwargs.pop("rig", None) or WorkerRig(
            fake_host, n_chips=kwargs.pop("n_chips", 4))
        stack = MultiMasterStack(rig, **kwargs)
        stacks.append(stack)
        return stack

    yield make
    for stack in stacks:
        stack.close()


def wait_until(pred, timeout_s=10.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while not pred():
        if time.monotonic() >= deadline:
            raise TimeoutError(f"timed out waiting for {what}")
        time.sleep(0.03)


# -- forwarding ----------------------------------------------------------------

def test_non_owner_proxies_to_leader(mm_factory):
    stack = mm_factory(masters=2, shards=2)
    stack.wait_converged()
    leader = stack.leader_for("default")
    follower = [i for i in stack.live() if i != leader][0]
    status, _, payload = req(stack.bases[follower],
                              "GET", add_path("workload", 2))
    assert status == 200 and payload["result"] == "SUCCESS"
    assert payload.get("forwarded_shard") == \
        stack.ring.shard_of("default")
    # the lease landed on the LEADER's broker, nowhere else
    assert len(stack.gateways[leader].broker.leases.leases()) == 1
    assert stack.gateways[follower].broker.leases.leases() == []
    # detach through the follower too: same forwarding, full cycle
    status, _, payload = req(stack.bases[follower], "POST",
                              remove_path("workload"), body=b"{}")
    assert status == 200 and payload["result"] == "SUCCESS"
    assert stack.gateways[leader].broker.leases.leases() == []
    assert_broker_invariants(stack.gateways[leader].broker,
                             stack.rig.sim,
                             store=stack.gateways[leader].broker.store)


def test_redirect_mode_returns_307_with_location(mm_factory):
    stack = mm_factory(masters=2, shards=2, forward="redirect")
    stack.wait_converged()
    leader = stack.leader_for("default")
    follower = [i for i in stack.live() if i != leader][0]
    path = add_path("workload", 2)
    status, headers, payload = req(stack.bases[follower], "GET", path)
    assert status == 307 and payload["result"] == "ShardRedirect"
    location = headers.get("Location")
    assert location == stack.bases[leader] + path
    # following the redirect (as any HTTP client would) succeeds
    parsed = urllib.parse.urlparse(location)
    status, _, payload = req(f"http://{parsed.netloc}", "GET",
                              parsed.path)
    assert status == 200 and payload["result"] == "SUCCESS"


def test_leaderless_shard_answers_503_with_retry_after(fake_host):
    """A gateway whose shard lock is held by an unreachable ghost (live
    deadline, no takeover possible) must shed with Retry-After, not hang
    or handle a shard it does not own."""
    stack = BrokerStack(fake_host)
    ha = HAConfig(shards=1, election=True, store=False, replica="m-local",
                  advertise_url="http://127.0.0.1:1",
                  renew_interval_s=0.1, lease_duration_s=30.0)
    # the ghost holds the lock with a far deadline and NO advertised url
    stack.kube.create_config_map(consts.DEFAULT_POOL_NAMESPACE, {
        "metadata": {
            "name": f"{consts.ELECTION_CONFIGMAP_PREFIX}0",
            "annotations": {
                "tpumounter.io/holder": "ghost",
                "tpumounter.io/url": "",
                consts.STORE_FENCE_ANNOTATION: "7",
                "tpumounter.io/renew-unix":
                    f"{time.time() + 300:.3f}"}}})
    gw = MasterGateway(stack.kube,
                       WorkerDirectory(stack.kube, grpc_port=stack.port),
                       broker=AttachBroker(stack.kube, BrokerConfig()),
                       ha=ha)
    gw.election.tick()
    assert not gw.election.is_leader(0)
    status, payload = gw.handle("GET", add_path("workload", 2))
    assert status == 503 and payload["result"] == "ShardLeaderUnknown"
    assert payload["retry_after_s"] >= 0.1
    # a request a peer ALREADY forwarded must not ping-pong
    status, payload = gw.handle("GET", add_path("workload", 2),
                                headers={"X-Tpu-Forwarded": "1"})
    assert status == 503 and payload["result"] == "ShardLeaderUnknown"
    stack.close()


# -- shard hand-off wakes waiters ----------------------------------------------

def test_lost_shard_wakes_waiters_to_reroute(fake_host):
    stack = BrokerStack(fake_host,
                        config=BrokerConfig(queue_timeout_s=20.0),
                        extra_pods=("w2",))
    broker = stack.gateway.broker
    ring = ShardRing(1)
    broker.bind_ha(None, ring, NullElection(1))
    from tests.test_broker import add
    assert add(stack.gateway, "workload", 4, entire=True)[0] == 200
    done = {}

    def park():
        done["res"] = add(stack.gateway, "w2", 2, rid="moved-1")

    thread = threading.Thread(target=park, daemon=True)
    thread.start()
    wait_until(lambda: broker._waiters, what="waiter to park")
    broker.on_shard_lost(0)
    thread.join(timeout=10)
    assert not thread.is_alive()
    status, payload = done["res"]
    assert status == 503 and payload["result"] == "ShardMoved"
    assert payload["retry_after_s"] >= 0.1
    stack.close()


# -- restart rehydration (single master, store on) -----------------------------

def test_restart_rehydrates_leases_and_parked_waiters(fake_host):
    config = BrokerConfig(queue_timeout_s=4.0)
    stack = BrokerStack(fake_host, config=config, extra_pods=("w2",))
    kube = stack.kube
    ring = ShardRing(1)
    store = IntentStore(kube, ring, consts.DEFAULT_POOL_NAMESPACE)
    old_gw = stack.gateway
    old_gw.broker.bind_ha(store, ring, NullElection(1))
    from tests.test_broker import add
    status, body = add(old_gw, "workload", 4, entire=True, rid="hold-1")
    assert status == 200
    held_uuids = set(body["device_ids"])
    done = {}

    def park():
        done["res"] = add(old_gw, "w2", 2, rid="park-1")

    thread = threading.Thread(target=park, daemon=True)
    thread.start()
    wait_until(lambda: store.rehydrate(0)[1], what="waiter persisted")

    # "restart": a fresh gateway + broker + store over the same cluster.
    # The old process's memory is irrelevant from here on.
    new_store = IntentStore(kube, ShardRing(1),
                            consts.DEFAULT_POOL_NAMESPACE)
    new_gw = stack.new_gateway(config)
    new_gw.broker.bind_ha(new_store, ShardRing(1), NullElection(1))
    new_gw.broker.bind_attempt_factory(new_gw._adopted_attempt)
    new_gw.broker.tick()              # lazy boot pass: rehydrate + adopt
    # the lease came back EXACT (uuids known, not a collapsed derivation)
    lease = new_gw.broker.leases.get("default", "workload")
    assert lease is not None and lease.uuids == held_uuids
    wait_until(lambda: new_gw.broker._waiters,
               what="adopted waiter to park")

    # freeing capacity on the NEW master drains the adopted waiter
    from tests.test_broker import remove
    assert remove(new_gw, "workload")[0] == 200
    wait_until(lambda: new_gw.broker.leases.get("default", "w2"),
               what="adopted waiter to be granted")
    thread.join(timeout=10)
    assert not thread.is_alive()
    # the original client (whose master "died") timed out cleanly; its
    # intent was fulfilled server-side under the SAME rid, so a retry
    # would adopt the attached chips instead of double-attaching
    status, payload = done["res"]
    assert status == 503 and payload.get("queue_timeout")
    wait_events_drained(stack.rig.service)
    assert_broker_invariants(new_gw.broker, stack.rig.sim,
                             store=new_store)
    w2_lease = new_gw.broker.leases.get("default", "w2")
    assert_invariants(stack.rig, set(w2_lease.uuids), owner="w2",
                      max_attached_events=2)
    stack.close()


# -- the acceptance chaos plan -------------------------------------------------

def test_leader_killed_mid_queue_peer_drains_persisted_waiters(
        mm_factory):
    """Kill the leading master while its queue holds two persisted
    waiters: the surviving replica assumes the shard within one renew
    interval of lock expiry, rehydrates the parked intent from the
    store, and every waiter resolves — both attaches land exactly once
    (zero double-actuation, zero leaked reservations), pinned by the
    node-local AND cross-replica broker invariants."""
    stack = mm_factory(masters=2, shards=2,
                       broker_config=BrokerConfig(queue_timeout_s=8.0),
                       renew_interval_s=0.15, lease_duration_s=0.45)
    rig = stack.rig
    for name in ("w2", "w3"):
        pod = rig.sim.add_target_pod(name=name)
        rig.provision_container(pod)
    stack.wait_converged()
    leader = stack.leader_for("default")
    survivor = [i for i in stack.live() if i != leader][0]
    shard = stack.ring.shard_of("default")

    status, _, body = req(stack.bases[leader], "GET",
                           add_path("workload", 4, entire=True),
                           headers={"X-Request-Id": "hold-1"})
    assert status == 200

    results = {}

    def park(pod, rid):
        try:
            results[rid] = req(stack.bases[leader], "GET",
                                add_path(pod, 2),
                                headers={"X-Request-Id": rid},
                                timeout=20.0)
        except OSError as e:
            # the master died under this client — in production it
            # retries the SAME rid against the service VIP and adopts
            results[rid] = ("dead-master", str(e))

    threads = [threading.Thread(target=park, args=(pod, rid),
                                daemon=True)
               for pod, rid in (("w2", "park-a"), ("w3", "park-b"))]
    for thread in threads:
        thread.start()
    leader_store = stack.gateways[leader].broker.store
    wait_until(lambda: len(leader_store.rehydrate(shard)[1]) == 2,
               what="both waiters persisted")

    stack.kill(leader)
    surv_gw = stack.gateways[survivor]
    wait_until(lambda: surv_gw.election.is_leader(shard),
               timeout_s=5.0, what="failover")
    wait_until(lambda: len(surv_gw.broker._waiters) == 2,
               what="adopted waiters to park on the survivor")

    # free the chips through the SURVIVOR: the adopted waiters drain
    status, _, _ = req(stack.bases[survivor], "POST",
                        remove_path("workload"), body=b"{}")
    assert status == 200
    wait_until(lambda: (surv_gw.broker.leases.get("default", "w2")
                        and surv_gw.broker.leases.get("default", "w3")),
               what="both adopted waiters granted")

    for thread in threads:
        thread.join(timeout=20)
        assert not thread.is_alive()

    wait_events_drained(rig.service)
    # zero double-actuation: one TPUAttached per logical attach
    attached = [e for e in rig.sim.kube.events
                if e.get("reason") == "TPUAttached"]
    assert len(attached) == 3, [e.get("message") for e in attached]
    # cross-replica view: the survivor's table AND the store both mirror
    # cluster ground truth; no waiter record outlived its resolution
    assert_broker_invariants(surv_gw.broker, rig.sim,
                             store=surv_gw.broker.store)
    expected = (set(surv_gw.broker.leases.get("default", "w2").uuids)
                | set(surv_gw.broker.leases.get("default", "w3").uuids))
    assert len(expected) == 4
    assert_invariants(rig, expected, owner="w2", max_attached_events=3)


# -- fleet view ----------------------------------------------------------------

def test_fleetz_shows_master_roles_and_store_lag(mm_factory):
    stack = mm_factory(masters=2, shards=2)
    stack.wait_converged()
    leader0 = stack.leader_for("default")
    snap = stack.gateways[leader0].fleet.snapshot()
    masters = snap["masters"]
    assert masters["enabled"] is True
    assert masters["replica"] == f"master-{leader0}"
    shards = masters["election"]["shards"]
    assert len(shards) == 2
    assert any(s["leader"] for s in shards.values())
    for s in shards.values():
        assert s["holder"].startswith("master-")
    assert masters["store"]["lag_s"] == 0.0
    status, _, payload = req(stack.bases[leader0], "GET", "/fleetz")
    assert status == 200 and "masters" in payload


def test_cli_fleet_renders_master_roles_and_store_lag(mm_factory):
    """`tpumounterctl fleet` shows the answering replica's role per
    shard and its store lag — a stuck failover is one command away."""
    import contextlib
    import io

    from gpumounter_tpu import cli

    stack = mm_factory(masters=2, shards=2)
    stack.wait_converged()
    leader = stack.leader_for("default")
    shard = stack.ring.shard_of("default")
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli.main(["--master", stack.bases[leader], "fleet"])
    rendered = out.getvalue()
    assert rc == 0, rendered
    assert f"master master-{leader}:" in rendered
    assert f"{shard}:LEADER" in rendered
    assert "store lag 0s" in rendered
    # a replica that leads NO shard still renders its follower view
    follower = [i for i in stack.live() if i != leader][0]
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        cli.main(["--master", stack.bases[follower], "fleet"])
    rendered = out.getvalue()
    assert f"master master-{follower}:" in rendered
    assert "LEADER" in rendered or "follower(" in rendered


# -- defaults pin --------------------------------------------------------------

def test_ha_defaults_off_preserve_single_master_semantics(fake_host):
    """The acceptance pin: a default HAConfig builds NO ring, NO
    election, NO store — a full attach + queue + detach cycle touches
    ZERO ConfigMaps (cluster traffic identical to PR 7), and the broker
    carries no HA section in /brokerz."""
    stack = BrokerStack(fake_host,
                        config=BrokerConfig(queue_timeout_s=0.3),
                        extra_pods=("w2",))
    gw = stack.gateway
    assert gw.ring is None and gw.election is None
    assert gw.broker.store is None
    assert HAConfig().enabled is False
    from tests.test_broker import add, remove
    assert add(gw, "workload", 4, entire=True)[0] == 200
    # exercise the queue path too (park + timeout): still no store write
    status, payload = add(gw, "w2", 2)
    assert status == 503 and payload.get("queue_timeout")
    assert remove(gw, "workload")[0] == 200
    assert stack.kube.cm_calls == 0, \
        "HA-off master generated ConfigMap traffic"
    snap = gw.broker.snapshot()
    assert snap["ha"] == {"enabled": False}
    # and the route gate is inert: no forwarded/redirect answers exist
    assert gw._shard_gate("default", "GET", "/x", b"", "-", {}) is None
    stack.close()


def test_shard_acquired_without_store_still_rederives(fake_host):
    """Review fix: TPU_ELECTION=1 with TPU_INTENT_STORE=0 is legal —
    a failover must still force re-derivation of the dead leader's
    leases from slave-pod ground truth (without a store that is the
    ONLY source), not early-return before resetting the flag."""
    stack = BrokerStack(fake_host)
    broker = stack.gateway.broker
    broker.bind_ha(None, ShardRing(1), NullElection(1))
    broker.ensure_rederived()
    assert broker._rederived is True
    broker.on_shard_acquired(0)
    assert broker._rederived is False, \
        "store-less failover skipped lease re-derivation"
    stack.close()


def test_sharded_slice_rejects_mixed_namespaces(mm_factory):
    """Review fix: sharded admission is keyed on namespace, so a slice
    spanning namespaces would record foreign-shard leases this replica
    never persists or reaps — it must be a 400, not a silent accept."""
    stack = mm_factory(masters=2, shards=2)
    stack.wait_converged()
    body = json.dumps({"pods": [
        {"namespace": "default", "pod": "a"},
        {"namespace": "other", "pod": "b"}], "tpusPerHost": 2}).encode()
    status, _, payload = req(stack.bases[0], "POST", "/addtpuslice",
                             body=body)
    assert status == 400 and "span namespaces" in payload["message"]
    status, _, payload = req(stack.bases[0], "POST", "/removetpuslice",
                             body=body)
    assert status == 400 and "span namespaces" in payload["message"]


# -- doctor --------------------------------------------------------------------

def _fake_doctor_fetch(monkeypatch, fleetz_masters, metrics_scrapes=None):
    """Route doctor's surface fetches: /healthz JSON, /metrics from the
    scrape list (last entry repeats), /fleetz with the given masters
    section; everything else 404s like a real single-binary target."""
    from gpumounter_tpu import cli
    scrapes = list(metrics_scrapes or [""])

    def fake_fetch(master, path, timeout):
        if path == "/healthz":
            return '{"status": "ok"}'
        if path == "/metrics":
            return scrapes.pop(0) if len(scrapes) > 1 else scrapes[0]
        if path.startswith("/fleetz"):
            return json.dumps({"nodes": {}, "masters": fleetz_masters})
        raise cli.TransportError(f"GET {path}: 404")

    monkeypatch.setattr(cli, "_fetch_text", fake_fetch)
    monkeypatch.setattr(cli.time, "sleep", lambda s: None)


def test_doctor_crits_on_leaderless_shard(monkeypatch):
    """A shard whose lock is expired with nobody local holding it means
    admission for its keyspace is DOWN — that pages, it does not WARN."""
    from gpumounter_tpu import cli
    from tests.test_cli import run_cli
    _fake_doctor_fetch(monkeypatch, {
        "enabled": True, "replica": "master-0", "shards": 2,
        "election": {"enabled": True, "shards": {
            "0": {"holder": "master-0", "fence": 3, "expires_in_s": 4.0,
                  "leader": True},
            "1": {"holder": "master-dead", "fence": 2,
                  "expires_in_s": -7.0, "leader": False}}},
        "store": {"lag_s": 0.0, "dirty": 0, "torn_records": 0}})
    rc, out = run_cli("http://unused", "doctor")
    assert rc == cli.EXIT_DOCTOR_CRIT, out
    assert "shard(s) 1 have NO live leader" in out


def test_doctor_healthy_ha_and_store_lag_warn(monkeypatch):
    from tests.test_cli import run_cli
    masters = {
        "enabled": True, "replica": "master-0", "shards": 1,
        "election": {"enabled": True, "shards": {
            "0": {"holder": "master-0", "fence": 1, "expires_in_s": 5.0,
                  "leader": True}}},
        "store": {"lag_s": 0.0, "dirty": 0, "torn_records": 0}}
    _fake_doctor_fetch(monkeypatch, masters)
    rc, out = run_cli("http://unused", "doctor")
    assert rc == 0, out
    assert "every shard has a live leader" in out
    # a lagging store degrades what a failover would rehydrate: WARN
    masters["store"] = {"lag_s": 12.5, "dirty": 3, "torn_records": 0}
    rc, out = run_cli("http://unused", "doctor")
    assert rc == 1, out
    assert "intent store lagging 12.5s" in out


def test_doctor_warns_on_leadership_flapping_in_window(monkeypatch):
    """>FLAP_WARN transitions inside --window = the lock is bouncing;
    the same lifetime total without a window only informs."""
    from gpumounter_tpu import cli
    from tests.test_cli import run_cli
    masters = {
        "enabled": True, "replica": "master-0", "shards": 1,
        "election": {"enabled": True, "shards": {
            "0": {"holder": "master-0", "fence": 9, "expires_in_s": 5.0,
                  "leader": True}}},
        "store": {"lag_s": 0.0, "dirty": 0, "torn_records": 0}}
    family = "tpumounter_election_transitions_total"
    first = (f'{family}{{shard="0",outcome="acquired"}} 2\n'
             f'{family}{{shard="0",outcome="lost"}} 2\n')
    second = (f'{family}{{shard="0",outcome="acquired"}} 4\n'
              f'{family}{{shard="0",outcome="lost"}} 4\n')
    _fake_doctor_fetch(monkeypatch, masters, [first, second])
    rc, out = run_cli("http://unused", "doctor", "--window", "5")
    assert rc == 1, out
    assert "leadership flapping on shard(s) 0" in out
    assert f"(> {cli.FLAP_WARN} transitions" in out
    # lifetime totals: informational, exit 0
    _fake_doctor_fetch(monkeypatch, masters, [first])
    rc, out = run_cli("http://unused", "doctor")
    assert rc == 0, out
    assert "leadership transitions: 4 — lifetime" in out


def test_tick_routes_flush_dirty_fence_to_demotion(fake_host):
    """Review fix: a dirty-queue replay bouncing off the fence must run
    the same note_fence+demote recovery as a direct write — and the
    tick must survive it (gauges still refresh), not abort."""
    from gpumounter_tpu.utils.errors import StoreFencedError

    class _Recorder:
        enabled = True

        def __init__(self):
            self.noted, self.demoted = [], []

        def is_leader(self, shard):
            return True

        def owned(self):
            return [0]

        def token(self, shard):
            return 1

        def note_fence(self, shard, fence):
            self.noted.append((shard, fence))

        def demote(self, shard, reason=""):
            self.demoted.append(shard)

    stack = BrokerStack(fake_host)
    broker = stack.gateway.broker
    election = _Recorder()
    broker.bind_ha(None, ShardRing(1), election)

    class _FencingStore:
        def flush_pending(self):
            return 0              # group-commit backstop: nothing queued

        def flush_dirty(self):
            raise StoreFencedError(0, 1, 7)

        def rehydrate(self, shard):
            return [], [], 0

        def stop(self):
            pass

    broker.store = _FencingStore()
    broker._rehydrated_shards.add(0)
    broker.tick()                     # must not raise
    assert election.noted == [(0, 7)]
    assert election.demoted == [0]
    stack.close()


def test_lost_shard_prunes_adoption_history(fake_host):
    """Review fix: adoption history is per-shard — a lose/reacquire
    cycle must re-adopt records the interim leader never resolved, so
    on_shard_lost prunes exactly the lost shard's rids."""
    stack = BrokerStack(fake_host)
    broker = stack.gateway.broker
    ring = ShardRing(2)
    broker.bind_ha(None, ring, NullElection(2))
    broker._adopted_rids = {"rid-s0": 0, "rid-s1": 1}
    broker.on_shard_lost(0)
    assert broker._adopted_rids == {"rid-s1": 1}
    stack.close()


def test_doctor_clean_multishard_failover_is_not_flapping(monkeypatch):
    """Review fix: one replica dying hands each of its 4 shards to the
    survivor — 4 'acquired' increments in one window. That is ONE clean
    failover, judged per shard (like the shipped alert rule), not 4
    aggregate transitions reading as churn."""
    from tests.test_cli import run_cli
    masters = {
        "enabled": True, "replica": "master-1", "shards": 4,
        "election": {"enabled": True, "shards": {
            str(s): {"holder": "master-1", "fence": 2,
                     "expires_in_s": 5.0, "leader": True}
            for s in range(4)}},
        "store": {"lag_s": 0.0, "dirty": 0, "torn_records": 0}}
    family = "tpumounter_election_transitions_total"
    first = "".join(
        f'{family}{{shard="{s}",outcome="acquired"}} 0\n'
        for s in range(4))
    second = "".join(
        f'{family}{{shard="{s}",outcome="acquired"}} 1\n'
        for s in range(4))
    _fake_doctor_fetch(monkeypatch, masters, [first, second])
    rc, out = run_cli("http://unused", "doctor", "--window", "5")
    assert rc == 0, out
    assert "flapping" not in out
    assert "leadership transitions: 4" in out


def test_store_only_config_still_surfaces_store_health(fake_host):
    """Review fix: TPU_INTENT_STORE=1 with TPU_ELECTION=0 (the durable
    single-master config) must still show store lag in /fleetz and
    doctor — a lagging store is exactly what a restart would lose."""
    stack = BrokerStack(fake_host)
    ha = HAConfig(shards=1, election=False, store=True,
                  replica="m-solo")
    gw = MasterGateway(stack.kube,
                       WorkerDirectory(stack.kube, grpc_port=stack.port),
                       broker=AttachBroker(stack.kube, BrokerConfig()),
                       ha=ha)
    view = gw._ha_view()
    assert view["enabled"] is True
    assert view["store"]["lag_s"] == 0.0
    assert view["election"]["enabled"] is False
    stack.close()


def test_cli_fleet_renders_store_only_masters_section(monkeypatch):
    """Review fix: store-only HA (election off) reports election shards
    as a COUNT, not a dict — the fleet CLI must render the store lag
    line, not crash iterating an int."""
    from tests.test_cli import run_cli
    _fake_doctor_fetch(monkeypatch, {
        "enabled": True, "replica": "m-solo", "shards": 1,
        "election": {"enabled": False, "shards": 1},
        "store": {"lag_s": 2.5, "dirty": 1, "torn_records": 0}})
    rc, out = run_cli("http://unused", "fleet")
    assert "master m-solo:" in out
    assert "store lag 2.5s (1 dirty)" in out
