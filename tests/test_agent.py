"""Resident actuation agent (actuation/agent.py): cached ns handles,
fd-liveness revalidation, in-process batch execution, and — the part that
keeps chaos honest — every fault path degrading to the fallback actuator
with the journal/rollback invariants intact."""

import os
import shutil

import pytest

from gpumounter_tpu.actuation.agent import (AgentActuator, AgentFault,
                                            ResidentActuationAgent)
from gpumounter_tpu.actuation.nsenter import RecordingActuator
from gpumounter_tpu.testing.chaos import assert_invariants
from gpumounter_tpu.testing.sim import WorkerRig
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.errors import ActuationError, TPUMounterError
from gpumounter_tpu.utils.metrics import REGISTRY


PID = 4242


@pytest.fixture
def agent(fake_host):
    os.makedirs(os.path.join(fake_host.proc_root, str(PID), "root", "dev"),
                exist_ok=True)
    a = ResidentActuationAgent(fake_host, fake_nodes=True)
    yield a
    a.stop()


def _container_nodes(fake_host, pid=PID):
    root = os.path.join(fake_host.proc_root, str(pid), "root")
    out = set()
    for dirpath, _, files in os.walk(root):
        for name in files:
            if not name.endswith(".majmin"):
                out.add("/" + os.path.relpath(os.path.join(dirpath, name),
                                              root))
    return out


# -- batch execution ----------------------------------------------------------

def test_agent_executes_batch_with_zero_forks(agent, fake_host):
    created = agent.apply(PID, [("/dev/accel0", 120, 0),
                                ("/dev/accel1", 120, 1)], [])
    assert created == 2
    assert _container_nodes(fake_host) == {"/dev/accel0", "/dev/accel1"}
    # sidecars carry the majmin (the shared fixture format)
    root = os.path.join(fake_host.proc_root, str(PID), "root")
    with open(root + "/dev/accel0.majmin") as f:
        assert f.read() == "120:0"


def test_agent_batches_are_idempotent(agent):
    assert agent.apply(PID, [("/dev/accel0", 120, 0)], []) == 1
    # existing node short-circuits: the resume signal is 0 new nodes
    assert agent.apply(PID, [("/dev/accel0", 120, 0)], []) == 0


def test_agent_removes_nodes_and_sidecars(agent, fake_host):
    agent.apply(PID, [("/dev/accel0", 120, 0)], [])
    agent.apply(PID, [], ["/dev/accel0"])
    assert _container_nodes(fake_host) == set()
    # removing an absent node is a no-op, not an error
    agent.apply(PID, [], ["/dev/accel0"])


def test_agent_caches_the_ns_handle(agent):
    assert agent.warm(PID) is True
    before = REGISTRY.agent_revalidations.value(outcome="ok")
    agent.apply(PID, [("/dev/accel0", 120, 0)], [])
    agent.apply(PID, [], ["/dev/accel0"])
    # both batches revalidated the SAME cached handle
    assert REGISTRY.agent_revalidations.value(outcome="ok") >= before + 2
    assert [h["pid"] for h in agent.status()["ns_fds"]] == [PID]


# -- fault paths --------------------------------------------------------------

def test_stale_handle_is_evicted_and_reopened(agent, fake_host):
    """Container restarted between warm and attach: the pid dir is a NEW
    inode, the cached handle flunks revalidation, and the agent reopens
    against the new incarnation transparently."""
    agent.warm(PID)
    pid_dir = os.path.join(fake_host.proc_root, str(PID))
    shutil.rmtree(pid_dir)
    os.makedirs(os.path.join(pid_dir, "root", "dev"))
    stale_before = REGISTRY.agent_revalidations.value(outcome="stale")
    assert agent.apply(PID, [("/dev/accel0", 120, 0)], []) == 1
    assert REGISTRY.agent_revalidations.value(outcome="stale") \
        == stale_before + 1
    assert _container_nodes(fake_host) == {"/dev/accel0"}


def test_dead_container_raises_agent_fault(agent, fake_host):
    agent.warm(PID)
    shutil.rmtree(os.path.join(fake_host.proc_root, str(PID)))
    with pytest.raises(AgentFault):
        agent.apply(PID, [("/dev/accel0", 120, 0)], [])


def test_actuation_error_passes_through_not_agent_fault(agent, fake_host):
    """Filesystem-level failures are genuine actuation failures: falling
    back would fail identically, and the rollback path needs the typed
    error. (Trigger: the node's parent path is occupied by a FILE, so
    mkdir fails — permission-based triggers don't bite under root.)"""
    root = os.path.join(fake_host.proc_root, str(PID), "root")
    with open(os.path.join(root, "dev", "blocked"), "w"):
        pass
    with pytest.raises(ActuationError):
        agent.apply(PID, [("/dev/blocked/accel0", 120, 7)], [])


def test_executor_crash_mid_batch_faults_then_recovers(agent):
    """An agent crash mid-batch surfaces as AgentFault to the submitter
    (who falls back); the executor keeps serving, and an idempotent
    retry of the half-applied batch completes it (accel0 landed before
    the crash, so only accel1 is new)."""
    calls = {"n": 0}

    def die_on_second(op, pid, path):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected agent crash")

    agent._op_hook = die_on_second
    with pytest.raises(AgentFault):
        agent.apply(PID, [("/dev/accel0", 120, 0),
                          ("/dev/accel1", 120, 1)], [])
    agent._op_hook = None
    assert agent.apply(PID, [("/dev/accel0", 120, 0),
                             ("/dev/accel1", 120, 1)], []) == 1
    assert agent.status()["executor_alive"] is True


def test_stopped_agent_faults_instead_of_hanging(agent):
    agent.stop()
    with pytest.raises(AgentFault):
        agent.apply(PID, [("/dev/accel0", 120, 0)], [])


# -- the AgentActuator fallback seam ------------------------------------------

def test_agent_fault_falls_back_to_wrapped_actuator(fake_host):
    """The container never existed for the agent (no pid dir): every op
    degrades to the fallback actuator and is counted."""
    agent = ResidentActuationAgent(fake_host, fake_nodes=True)
    fallback = RecordingActuator()
    actuator = AgentActuator(agent, fallback)
    before = REGISTRY.agent_fallbacks.value(reason="open_ns_fd")
    try:
        made = actuator.apply_device_nodes(9999, [("/dev/accel0", 1, 2)],
                                           [])
        assert made == 1
        assert fallback.created == [(9999, "/dev/accel0", 1, 2)]
        assert REGISTRY.agent_fallbacks.value(reason="open_ns_fd") \
            == before + 1
    finally:
        agent.stop()


def test_single_op_methods_ride_the_agent(agent, fake_host):
    actuator = AgentActuator(agent, RecordingActuator())
    assert actuator.create_device_node(PID, "/dev/accel0", 120, 0) is True
    assert actuator.create_device_node(PID, "/dev/accel0", 120, 0) is False
    actuator.remove_device_node(PID, "/dev/accel0")
    assert _container_nodes(fake_host) == set()


# -- service-level chaos: journal / rollback interplay ------------------------

def _attach(rig, request_id="agent-chaos"):
    return rig.service.add_tpu("workload", "default", 4, True,
                               request_id=request_id)


def test_agent_crash_mid_batch_fallback_completes_attach(fake_host):
    """Agent dies between the cgroup grant and the last mknod: the
    fallback actuator idempotently completes the batch and the attach
    SUCCEEDS — invariants hold, journal clean."""
    rig = WorkerRig(fake_host, n_chips=4, actuator="procroot",
                    informer=True, agent=True)
    calls = {"n": 0}

    def die_once_mid_batch(op, pid, path):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected agent crash mid-batch")

    rig.agent._op_hook = die_once_mid_batch
    try:
        outcome = _attach(rig)
        assert outcome.result == consts.AddResult.SUCCESS
        assert calls["n"] >= 2          # the crash actually fired
        assert_invariants(rig, {c.uuid for c in outcome.chips})
    finally:
        rig.close()


def test_agent_crash_plus_fallback_failure_rolls_back(fake_host):
    """Agent dies mid-batch AND the fallback fails: the service's normal
    rollback runs (slave pods deleted, partial nodes reverted, journal
    reverted) — the chaos contract the journal exists for."""
    rig = WorkerRig(fake_host, n_chips=4, actuator="procroot",
                    informer=True, agent=True)

    def always_die(op, pid, path):
        raise RuntimeError("injected agent crash")

    rig.agent._op_hook = always_die
    fallback = rig.actuator.fallback
    orig = fallback.create_device_node

    def failing_create(pid, device_path, major, minor,
                       mode=consts.DEVICE_FILE_MODE):
        raise ActuationError("injected fallback failure")

    fallback.create_device_node = failing_create
    try:
        with pytest.raises(TPUMounterError):
            _attach(rig)
        fallback.create_device_node = orig
        rig.agent._op_hook = None
        assert_invariants(rig, set())
        assert rig.service.journal.backlog() == 0
    finally:
        rig.close()


def test_agent_attach_detach_cycle_end_to_end(fake_host):
    rig = WorkerRig(fake_host, n_chips=4, actuator="procroot",
                    informer=True, agent=True)
    try:
        outcome = _attach(rig)
        assert outcome.result == consts.AddResult.SUCCESS
        status = rig.agent.status()
        assert status["executor_alive"] is True
        assert status["ns_fds"], "attach did not warm an ns handle"
        assert rig.service.remove_tpu("workload", "default", [],
                                      False).result \
            == consts.RemoveResult.SUCCESS
        assert_invariants(rig, set(), max_attached_events=1)
    finally:
        rig.close()
