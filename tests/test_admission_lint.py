"""Admission-layer lint (pattern of test_retry_lint / test_informer_lint):
every mutating gateway route must pass through the attach broker's
admission layer — structurally, no route may reach ``_add`` /
``_slice_attach`` without an ``admit()`` call in its path, and no gateway
method may fire an attach RPC outside the broker's orchestration. A new
mutating route added without admission wiring fails here instead of
shipping a quota bypass."""

from gpumounter_tpu.master import admission, gateway, slicetxn

from tests.test_retry_lint import (_functions, _names_used,
                                   _referencing_functions)


def test_attach_handlers_only_dispatched_from_route():
    """The only caller of the attach handlers is the method-checked
    dispatcher — there is no side door around tenant/priority parsing."""
    assert _referencing_functions(gateway, "_add") == \
        {"MasterGateway._route"}
    assert _referencing_functions(gateway, "_slice_attach") == \
        {"MasterGateway._route"}
    assert _referencing_functions(gateway, "_slice_resize") == \
        {"MasterGateway._route"}


def test_add_routes_through_the_broker():
    """_add never dials the worker directly: the RPC lives in a closure
    the broker invokes (admission, queueing, preemption wrap it)."""
    funcs = _functions(gateway)
    names = _names_used(funcs["MasterGateway._add"])
    assert "broker" in names, "_add bypasses the attach broker"
    assert "attach" in names, "_add does not use broker.attach"


def test_slice_attach_admits_before_fanout():
    """Slice admission moved into the txn manager (master/slicetxn.py)
    with the crash-safe protocol: both gateway slice-mutation handlers
    route through it, and the manager's transaction entry runs under the
    broker's reservation-scoped admission context — the whole gang wait
    stays inside the reservation, so a parked slice's chips count as
    in-flight usage against its tenant's cap."""
    funcs = _functions(gateway)
    for handler in ("MasterGateway._slice_attach",
                    "MasterGateway._slice_resize"):
        names = _names_used(funcs[handler])
        assert "slices" in names, \
            f"{handler} bypasses the slice txn manager"
    txn_funcs = _functions(slicetxn)
    attach_names = _names_used(txn_funcs["SliceTxnManager.attach"])
    assert "admission" in attach_names, \
        "SliceTxnManager.attach skips reservation-scoped quota admission"
    resize_names = _names_used(txn_funcs["SliceTxnManager.resize"])
    assert "attach" in resize_names, \
        "resize's grow half must run as an admitted slice txn"
    # the raw coordinator (which holds the per-host add_tpu calls) is
    # only reachable from the admitted detach handler and the manager
    assert _referencing_functions(gateway, "_slice_coordinator") == \
        {"MasterGateway._slice_detach"}
    assert _referencing_functions(slicetxn, "SliceCoordinator") == \
        {"SliceTxnManager._coordinator"}


def test_broker_attach_cannot_skip_admission():
    """The broker's own orchestration entry runs under the
    reservation-scoped admission() context, which calls admit() — the
    one admission authority (decision counter + typed denial), not a
    re-implementable check."""
    funcs = _functions(admission)
    assert "admission" in _names_used(funcs["AttachBroker.attach"])
    assert "admit" in _names_used(funcs["AttachBroker.admission"])
    assert "_inflight" in _names_used(funcs["AttachBroker.admission"])
    admit_names = _names_used(funcs["AttachBroker.admit"])
    assert "admission_decisions" in admit_names
    assert "QuotaExceededError" in admit_names
    # usage comes from the lease table (live state), never a local tally
    assert "leases" in admit_names


def test_every_gateway_attach_rpc_site_is_broker_gated():
    """Any MasterGateway method that references the attach RPC
    (add_tpu) must also reference the broker — a future route that
    hand-rolls a worker attach without admission fails here."""
    for qual, funcdef in _functions(gateway).items():
        parts = qual.split(".")
        if len(parts) != 2 or parts[0] != "MasterGateway":
            continue        # nested defs are counted inside their method
        names = _names_used(funcdef)
        if "add_tpu" in names:
            assert "broker" in names, \
                f"{qual} fires an attach RPC outside the admission layer"
