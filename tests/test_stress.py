"""Concurrency storms over the live gRPC worker: many pods mutating one
node's chips in parallel. Asserts the invariants that matter under
contention — no chip double-grant, exact scheduler accounting, no leaked
slave pods after failures — complementing the same-pod serialization tests
in test_idempotency.py."""

import os
import threading
import time

import pytest

from gpumounter_tpu.utils import consts
from gpumounter_tpu.worker.grpc_server import WorkerClient, build_server
from tests.helpers import WorkerRig


@pytest.fixture
def grpc_rig(fake_host):
    rig = WorkerRig(fake_host, n_chips=8)
    server, port = build_server(rig.service, port=0, address="127.0.0.1")
    server.start()
    client = WorkerClient(f"127.0.0.1:{port}")
    yield rig, client
    client.close()
    server.stop(grace=0)
    rig.close()


def _add_pods(rig, names):
    for name in names:
        pod = rig.sim.add_target_pod(name=name)
        rig.provision_container(pod)


def test_parallel_attach_detach_isolation(grpc_rig):
    """4 pods x 2 chips in parallel on an 8-chip node: all succeed, chip
    sets are disjoint, and parallel detach returns the node to empty."""
    rig, client = grpc_rig
    pods = [f"pod-{i}" for i in range(4)]
    _add_pods(rig, pods)

    results: dict[str, object] = {}

    def attach(name):
        results[name] = client.add_tpu(name, "default", 2,
                                       is_entire_mount=True,
                                       request_id=f"rid-{name}")

    threads = [threading.Thread(target=attach, args=(p,)) for p in pods]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)

    assert all(r.result == 0 for r in results.values()), results
    all_ids = [i for r in results.values() for i in r.device_ids]
    assert len(all_ids) == 8
    assert len(set(all_ids)) == 8          # no chip granted twice
    assert len(rig.sim.slave_pods()) == 4

    def detach(name):
        results[name] = client.remove_tpu(
            name, "default", list(results[name].device_ids), force=False)

    threads = [threading.Thread(target=detach, args=(p,)) for p in pods]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert all(r.result == 0 for r in results.values())
    assert rig.sim.slave_pods() == []
    # every chip is FREE again
    rig.sim.collector.update_status()
    from gpumounter_tpu.device.model import DeviceState
    assert all(c.state is DeviceState.FREE
               for c in rig.sim.collector.chips)


def test_contention_exact_accounting(grpc_rig):
    """8 pods race for 2 chips each on an 8-chip node: exactly 4 attaches
    can win; losers get INSUFFICIENT_TPU and leak nothing."""
    rig, client = grpc_rig
    pods = [f"racer-{i}" for i in range(8)]
    _add_pods(rig, pods)

    results: dict[str, object] = {}

    def attach(name):
        results[name] = client.add_tpu(name, "default", 2,
                                       is_entire_mount=True,
                                       request_id=f"rid-{name}")

    threads = [threading.Thread(target=attach, args=(p,)) for p in pods]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)

    winners = [n for n, r in results.items() if r.result == 0]
    losers = [n for n, r in results.items()
              if r.result == int(consts.AddResult.INSUFFICIENT_TPU)]
    assert len(winners) == 4, results
    assert len(losers) == 4
    won_ids = [i for n in winners for i in results[n].device_ids]
    assert len(won_ids) == 8 and len(set(won_ids)) == 8
    # losers' failed slave pods were cleaned up — only winners' remain
    assert len(rig.sim.slave_pods()) == 4
    holders = {p["metadata"]["labels"][consts.OWNER_POD_LABEL_KEY]
               for p in rig.sim.slave_pods()}
    assert holders == set(winners)


@pytest.mark.skipif(not os.path.isdir("/proc/self/fd"),
                    reason="needs procfs for fd counting")
def test_no_fd_thread_or_lock_leak_over_many_cycles(grpc_rig):
    """The worker is a months-lived daemon: every attach/detach cycle must
    return the process to baseline. Catches leaked sockets/pipes (open
    fds), orphaned threads, and growth in the per-request/per-pod lock
    tables and the event queue."""
    rig, client = grpc_rig
    _add_pods(rig, ["soak"])

    def cycle(i):
        resp = client.add_tpu("soak", "default", 2, False,
                              request_id=f"soak-{i}")
        assert resp.result == int(consts.AddResult.SUCCESS)
        out = client.remove_tpu("soak", "default",
                                list(resp.device_ids), False)
        assert out.result == int(consts.RemoveResult.SUCCESS)

    for i in range(5):                       # warm-up: lazy inits allocate
        cycle(i)
    fds_before = len(os.listdir("/proc/self/fd"))
    threads_before = threading.active_count()

    for i in range(5, 35):
        cycle(i)

    fds_after = len(os.listdir("/proc/self/fd"))
    threads_after = threading.active_count()
    # small tolerance: the event worker thread and a gRPC poller may spin
    # up lazily, but growth must not scale with cycle count
    assert fds_after - fds_before <= 3, (fds_before, fds_after)
    assert threads_after - threads_before <= 2, (threads_before,
                                                 threads_after)
    # refcounted lock tables drain to empty when no request is in flight
    assert rig.service._request_locks._entries == {}
    assert rig.service._pod_locks._entries == {}
    # bounded event queue drains (nothing stuck waiting on the apiserver);
    # the drain is async off the RPC path, so poll briefly
    deadline = time.monotonic() + 5.0
    while rig.service._event_queue and time.monotonic() < deadline:
        time.sleep(0.05)
    assert len(rig.service._event_queue) == 0
