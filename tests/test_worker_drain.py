"""Graceful worker drain (worker/drain.py): the controller's admit/
settle semantics, the typed 503 Draining across the gRPC + gateway
hops, the /drainz + healthz surfaces, the spot-termination watcher,
and the fault-free byte-for-byte pin. (jaxcheck checkpoint drain lives
in tests/test_drain.py — different subsystem.)"""

import json
import threading
import time
import urllib.error
import urllib.request

import grpc
import pytest

from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.master.discovery import WorkerDirectory
from gpumounter_tpu.master.gateway import MasterGateway
from gpumounter_tpu.testing.sim import (WorkerRig, make_target_pod,
                                        worker_pod)
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.errors import WorkerDrainingError
from gpumounter_tpu.utils.events import EVENTS
from gpumounter_tpu.worker.drain import (DrainController,
                                         SpotTerminationWatcher)
from gpumounter_tpu.worker.grpc_server import WorkerClient, build_server
from gpumounter_tpu.worker.main import start_health_server


# -- DrainController unit ------------------------------------------------------

def test_drain_refuses_new_attaches_but_admits_detaches():
    drain = DrainController("unit-node")
    with drain.inflight("attach"):
        pass                            # admitting while healthy
    drain.begin("test")
    with pytest.raises(WorkerDrainingError):
        with drain.inflight("attach"):
            pass
    with drain.inflight("detach"):      # drain frees capacity
        pass
    status = drain.status()
    assert status["draining"] is True
    assert status["refused"] == 1
    assert status["inflight"] == 0


def test_drain_waits_for_inflight_actuation_to_settle():
    drain = DrainController("unit-node")
    release = threading.Event()
    entered = threading.Event()

    def slow_attach():
        with drain.inflight("attach"):
            entered.set()
            release.wait(5.0)

    thread = threading.Thread(target=slow_attach, daemon=True)
    thread.start()
    assert entered.wait(2.0)
    drain.begin("test")
    assert drain.wait_settled(0.05) is False     # still in flight
    release.set()
    assert drain.wait_settled(2.0) is True
    thread.join(timeout=2.0)


def test_drain_run_sequence_flushes_and_events():
    drain = DrainController("drain-seq-node")
    assert drain.run(reason="unit") is True
    kinds = [e["kind"] for e in EVENTS.tail(200)
             if e.get("node") == "drain-seq-node"]
    assert kinds == ["drain_begin", "drain_complete"]
    assert drain.status()["completed_unix"] is not None
    # idempotent: a second begin is a no-op
    assert drain.begin("again") is False


def test_spot_watcher_triggers_drain_on_notice_file(tmp_path):
    fired = threading.Event()
    notice = tmp_path / "preempted"
    watcher = SpotTerminationWatcher(str(notice), fired.set,
                                     poll_interval_s=0.01).start()
    try:
        time.sleep(0.05)
        assert not fired.is_set()
        notice.write_text("TRUE")
        assert fired.wait(2.0)
        assert watcher.fired
    finally:
        watcher.stop()


# -- across the wire: worker refusal → typed 503 at the gateway ----------------

@pytest.fixture
def drain_stack(fake_host):
    """WorkerRig with a DrainController + live gRPC worker + gateway."""
    rig = WorkerRig(fake_host)
    rig.drain = DrainController(rig.sim.node)
    rig.service.drain = rig.drain
    server, port = build_server(rig.service, port=0, address="127.0.0.1")
    server.start()
    master_kube = FakeKubeClient()
    master_kube.put_pod(worker_pod("node-a", "127.0.0.1"))
    master_kube.put_pod(make_target_pod())
    gateway = MasterGateway(master_kube,
                            WorkerDirectory(master_kube, grpc_port=port))
    yield rig, gateway, port
    server.stop(grace=0)
    rig.close()


ADD = "/addtpu/namespace/default/pod/workload/tpu/1/isEntireMount/false"
REMOVE = "/removetpu/namespace/default/pod/workload/force/false"


def test_draining_worker_answers_typed_503_draining(drain_stack):
    rig, gateway, port = drain_stack
    rig.drain.begin("test")
    # raw gRPC: UNAVAILABLE with the draining: detail marker
    with WorkerClient(f"127.0.0.1:{port}") as client:
        with pytest.raises(grpc.RpcError) as err:
            client.add_tpu("workload", "default", 1, False,
                           request_id="rid-drain")
        assert err.value.code() == grpc.StatusCode.UNAVAILABLE
        assert err.value.details().startswith(
            consts.DRAINING_DETAIL_PREFIX)
    # gateway: typed 503 Draining + Retry-After hint, NOT a 502 —
    # and exactly ONE worker round trip (no transport-fault retries)
    status, payload = gateway.handle("GET", ADD)
    assert status == 503
    assert payload["result"] == "Draining"
    assert payload["retry_after_s"] > 0


def test_draining_worker_still_serves_detaches(drain_stack):
    rig, gateway, _ = drain_stack
    status, payload = gateway.handle("GET", ADD)
    assert status == 200, payload
    rig.drain.begin("test")
    status, payload = gateway.handle("POST", REMOVE)
    assert status == 200, payload
    assert payload["result"] == "SUCCESS"
    assert rig.drain.status()["refused"] == 0


def test_drain_refusal_is_not_a_breaker_failure(drain_stack):
    """Every retry of a draining worker gets the same answer — the
    gateway must neither retry nor count it toward the breaker (a
    draining node is healthy, not failing)."""
    rig, gateway, port = drain_stack
    rig.drain.begin("test")
    for _ in range(gateway.breaker_failure_threshold + 2):
        status, payload = gateway.handle("GET", ADD)
        assert status == 503
        assert payload["result"] == "Draining"
    breaker = gateway._breaker(f"127.0.0.1:{port}")
    breaker.allow()        # closed: would raise CircuitOpenError if open


# -- health surfaces -----------------------------------------------------------

def test_healthz_and_drainz_surfaces(fake_host):
    rig = WorkerRig(fake_host)
    drain = DrainController("node-a")
    rig.service.drain = drain
    server = start_health_server(0, journal=rig.journal, drain=drain,
                                 ready=True)
    base = f"http://127.0.0.1:{server.server_port}"
    try:
        with urllib.request.urlopen(base + "/healthz") as resp:
            assert resp.read() == b"ok"
        with urllib.request.urlopen(base + "/readyz") as resp:
            assert resp.status == 200
        with urllib.request.urlopen(base + "/drainz") as resp:
            payload = json.loads(resp.read())
        assert payload == {"enabled": True, **drain.status()}
        # POST /drainz begins the drain
        req = urllib.request.Request(base + "/drainz", method="POST",
                                     data=b"")
        with urllib.request.urlopen(req) as resp:
            payload = json.loads(resp.read())
        assert payload["started"] is True
        assert payload["draining"] is True
        # healthz says draining (still 200 — alive, just leaving);
        # readyz flips not-ready so the kubelet stops routing
        with urllib.request.urlopen(base + "/healthz") as resp:
            assert resp.read() == b"draining"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(base + "/readyz")
        assert err.value.code == 503
        # a second POST reports started=False (idempotent)
        with urllib.request.urlopen(req) as resp:
            assert json.loads(resp.read())["started"] is False
    finally:
        server.shutdown()
        rig.close()


def test_drainz_without_controller_answers_disabled(fake_host):
    rig = WorkerRig(fake_host)
    server = start_health_server(0, journal=rig.journal, ready=True)
    base = f"http://127.0.0.1:{server.server_port}"
    try:
        with urllib.request.urlopen(base + "/drainz") as resp:
            assert json.loads(resp.read()) == {"enabled": False}
        with urllib.request.urlopen(base + "/healthz") as resp:
            assert resp.read() == b"ok"
    finally:
        server.shutdown()
        rig.close()


# -- byte-for-byte pin ---------------------------------------------------------

def test_fault_free_path_with_idle_drain_is_byte_for_byte(fake_host,
                                                          tmp_path):
    """The drain subsystem wired but idle must not change ANYTHING
    about a normal attach/detach: same outcomes, same journal records,
    zero drain events."""
    import copy

    def run(with_drain: bool, host):
        rig = WorkerRig(host)
        if with_drain:
            rig.drain = DrainController(rig.sim.node)
            rig.service.drain = rig.drain
        try:
            add = rig.service.add_tpu("workload", "default", 2, False,
                                      request_id="rid-b4b")
            remove = rig.service.remove_tpu("workload", "default", [],
                                            False, request_id="rid-b4b2")
            records = copy.deepcopy(rig.journal.snapshot()["records"])
            for record in records:
                record.pop("ts", None)
                record.pop("jid", None)
                # slave-pod names carry a random suffix per run: the
                # comparison cares about count + record shape
                if "slaves" in record:
                    record["slaves"] = len(record["slaves"])
            return (add.result, sorted(c.uuid for c in add.chips),
                    remove.result, records)
        finally:
            rig.close()

    from gpumounter_tpu.utils.config import HostPaths
    tail = EVENTS.tail(1)
    seq0 = tail[-1]["seq"] if tail else 0
    base = tmp_path / "b4b"
    for sub in ("dev", "proc", "sys/fs/cgroup"):
        (base / sub).mkdir(parents=True)
    other = HostPaths(dev_root=str(base / "dev"),
                      proc_root=str(base / "proc"),
                      sys_root=str(base / "sys"),
                      cgroup_root=str(base / "sys" / "fs" / "cgroup"),
                      kubelet_socket=str(base / "pr" / "kubelet.sock"))
    with_drain = run(True, fake_host)
    without = run(False, other)
    assert with_drain == without
    assert not [e for e in EVENTS.tail(300)
                if e["seq"] > seq0
                and e["kind"] in ("drain_begin", "drain_complete",
                                  "spot_termination")]
