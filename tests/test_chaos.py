"""Chaos invariant suite: under ANY fault plan, attaches either converge
or roll back cleanly — no leaked slave-pod reservations, no partial device
grants, no journal backlog, no double TPUAttached events.

The matrix covers the transient-fault families (apiserver error bursts,
throttling with Retry-After, connection-level failures, injected latency,
watch hangs and mid-stream watch death, kubelet socket flaps) plus worker
crash-restart at every actuation phase boundary and an interrupted
rollback — the scenarios the retry layer, the watch-resume machinery, the
circuit breakers, and the attach journal exist for.
"""

import pytest

from gpumounter_tpu.testing.chaos import (CRASH_POINTS, ChaosRig, Fault,
                                          FaultPlan, WorkerCrash,
                                          assert_invariants,
                                          wait_events_drained)
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.errors import TPUMounterError

RID = "chaos-rid-1"
ALL_CHIPS = {"0", "1", "2", "3"}


def _attach(chaos, tpus=4, entire=True, rid=RID):
    return chaos.rig.service.add_tpu("workload", "default", tpus, entire,
                                     request_id=rid)


# -- the transient-fault matrix: every plan must CONVERGE ----------------------

TRANSIENT_PLANS = [
    FaultPlan(
        "connection_refused_on_create",
        [Fault(op="POST", resource="pods", status=0, cause="refused",
               times=2)],
        "slave-pod creates refused at the TCP level (provably never "
        "landed — safe to replay even for a POST)"),
    FaultPlan(
        "throttled_create_with_retry_after",
        [Fault(op="POST", resource="pods", status=429,
               retry_after_s=0.02, times=2)],
        "creates throttled: 429 is rejected-before-processing, replayable"),
    FaultPlan(
        "apiserver_429_with_retry_after",
        [Fault(op="LIST", resource="pods", status=429,
               retry_after_s=0.02, times=2)],
        "LISTs throttled; server-supplied Retry-After honored"),
    FaultPlan(
        "connection_refused_on_get",
        [Fault(op="GET", resource="pods", status=0, cause="refused",
               times=2)],
        "pod GETs refused at the TCP level twice"),
    FaultPlan(
        "injected_latency_on_get",
        [Fault(op="GET", resource="pods", latency_s=0.05, times=3)],
        "slow apiserver: 50ms added to three GETs"),
    FaultPlan(
        "watch_hang",
        [Fault(op="WATCH", resource="pods", latency_s=0.3, times=1)],
        "the scheduling watch stalls 300ms before delivering"),
    FaultPlan(
        "watch_midstream_death",
        [Fault(op="WATCH", resource="pods", status=0, cause="reset",
               times=2)],
        "the scheduling watch dies twice mid-stream; resume from rv"),
    FaultPlan(
        "kubelet_socket_flap",
        [Fault(op="LIST", resource="podresources", kubelet=True, times=2)],
        "kubelet PodResources socket flaps twice"),
    FaultPlan(
        "event_post_500s",
        [Fault(op="POST", resource="events", status=500, times=4)],
        "audit-event POSTs failing must never fail the attach"),
    FaultPlan(
        "mixed_storm",
        [Fault(op="POST", resource="pods", status=0, cause="refused",
               times=1),
         Fault(op="GET", resource="pods", status=0, cause="timeout",
               times=1),
         Fault(op="LIST", resource="podresources", kubelet=True, times=1),
         Fault(op="LIST", resource="pods", latency_s=0.02, times=2)],
        "a bit of everything at once"),
]


@pytest.mark.parametrize("plan", TRANSIENT_PLANS, ids=lambda p: p.name)
def test_attach_converges_under_transient_faults(plan, fake_host):
    # watch-focused plans need the pods to go Running AFTER the watch is
    # established, or the LIST-then-watch fast path never watches at all
    delay = 0.15 if plan.name.startswith("watch") else 0.0
    chaos = ChaosRig(fake_host, n_chips=4, plan=plan,
                     schedule_delay_s=delay)
    try:
        outcome = _attach(chaos)
        assert outcome.result == consts.AddResult.SUCCESS
        assert sorted(c.uuid for c in outcome.chips) == sorted(ALL_CHIPS)
        assert_invariants(chaos.rig, ALL_CHIPS)   # drains async events too
        assert chaos.injector.fired, "plan never bit — proves nothing"
    finally:
        chaos.close()


@pytest.mark.parametrize("plan", TRANSIENT_PLANS[:4] + TRANSIENT_PLANS[6:7],
                         ids=lambda p: p.name)
def test_full_attach_detach_cycle_under_faults(plan, fake_host):
    """Detach runs under the same plan's remaining faults; the node ends
    empty with zero leaked state."""
    chaos = ChaosRig(fake_host, n_chips=4, plan=plan)
    try:
        assert _attach(chaos).result == consts.AddResult.SUCCESS
        out = chaos.rig.service.remove_tpu("workload", "default", [], False)
        assert out.result == consts.RemoveResult.SUCCESS
        assert_invariants(chaos.rig, set(), max_attached_events=1)
    finally:
        chaos.close()


def test_retries_are_observable(fake_host):
    from gpumounter_tpu.utils.metrics import REGISTRY
    plan = FaultPlan("observable", [
        Fault(op="POST", resource="pods", status=0, cause="refused",
              times=1)])
    chaos = ChaosRig(fake_host, n_chips=4, plan=plan)
    try:
        before = REGISTRY.retry_attempts.value(target="apiserver")
        assert _attach(chaos).result == consts.AddResult.SUCCESS
        assert REGISTRY.retry_attempts.value(target="apiserver") > before
    finally:
        chaos.close()


# -- worker crash-restart at each actuation phase boundary ---------------------

@pytest.mark.parametrize("point", CRASH_POINTS)
def test_worker_crash_then_replay_completes_attach(point, fake_host):
    """Crash before/in the middle of/right after actuation: the journal
    intent survives, the restarted worker's replay COMPLETES the attach
    (owner alive, reservations intact), and exactly one logical attach is
    recorded."""
    chaos = ChaosRig(fake_host, n_chips=4)
    try:
        chaos.arm_crash(point)
        with pytest.raises(WorkerCrash):
            _attach(chaos)
        assert chaos.rig.journal.backlog() == 1     # intent survived
        outcomes = chaos.restart_worker()
        assert outcomes == {"completed": 1}
        assert_invariants(chaos.rig, ALL_CHIPS, max_attached_events=1)
        wait_events_drained(chaos.rig.service)
        reasons = [e["reason"] for e in chaos.rig.sim.kube.events]
        assert reasons.count("TPUAttached") == 0    # crash beat the event
        assert reasons.count("TPUAttachResumed") == 1
        # and the completed attach is fully functional: detach cleans up
        out = chaos.rig.service.remove_tpu("workload", "default", [], False)
        assert out.result == consts.RemoveResult.SUCCESS
        assert_invariants(chaos.rig, set(), max_attached_events=0)
    finally:
        chaos.close()


def test_worker_crash_then_owner_death_replay_reverts(fake_host):
    """Crash mid-attach AND the owner pod dies while the worker is down:
    replay must release the orphaned reservations instead of completing
    an attach into a dead pod."""
    chaos = ChaosRig(fake_host, n_chips=4)
    try:
        chaos.arm_crash("before_commit")
        with pytest.raises(WorkerCrash):
            _attach(chaos)
        # owner dies while the worker is "down"; its container (and every
        # device node in its mount namespace) dies with it
        chaos.rig.sim.kube.delete_pod("default", "workload")
        chaos.rig.actuator.created.clear()
        outcomes = chaos.restart_worker()
        assert outcomes == {"noop": 1} or outcomes == {"reverted": 1}
        assert chaos.rig.sim.slave_pods() == []     # reservations released
        assert chaos.rig.journal.backlog() == 0
    finally:
        chaos.close()


def test_replay_is_idempotent_for_committed_attaches(fake_host):
    """A restart with a fully committed journal replays NOTHING — no
    duplicate actuation, no duplicate events."""
    chaos = ChaosRig(fake_host, n_chips=4)
    try:
        assert _attach(chaos).result == consts.AddResult.SUCCESS
        created_before = list(chaos.rig.actuator.created)
        outcomes = chaos.restart_worker()
        assert outcomes == {}
        assert chaos.rig.actuator.created == created_before
        assert_invariants(chaos.rig, ALL_CHIPS)
    finally:
        chaos.close()


# -- satellite: rollback itself interrupted by apiserver failure ---------------

def test_interrupted_rollback_is_journaled_and_finished_by_replay(fake_host):
    """Actuation fails → rollback starts → the apiserver dies mid-revert
    (slave-pod deletes all fail). The leftover is journaled as
    revert_pending; the restarted worker's replay finishes the revert."""
    chaos = ChaosRig(fake_host, n_chips=4)
    try:
        chaos.rig.actuator.fail_on_create = True
        # deep burst: outlives the delete retries, so the rollback's
        # slave-pod deletes genuinely fail
        chaos.install(FaultPlan("apiserver_dies_mid_revert", [
            Fault(op="DELETE", resource="pods", status=503, times=50)]))
        with pytest.raises(TPUMounterError):
            _attach(chaos)
        assert chaos.rig.journal.backlog() == 1
        record = chaos.rig.journal.incomplete()[0]
        assert record["state"] == "revert_pending"
        assert len(chaos.rig.sim.slave_pods()) == 1   # the leftover

        # apiserver recovers; worker restarts
        chaos.rig.sim.kube.faults = None
        chaos.rig.actuator.fail_on_create = False
        outcomes = chaos.restart_worker()
        assert outcomes == {"reverted": 1}
        assert chaos.rig.sim.slave_pods() == []
        assert_invariants(chaos.rig, set(), max_attached_events=0)
    finally:
        chaos.close()


def test_clean_rollback_needs_no_replay(fake_host):
    """Contrast case: when the rollback completes in-process, the journal
    record is terminal and a restart replays nothing."""
    chaos = ChaosRig(fake_host, n_chips=4)
    try:
        chaos.rig.actuator.fail_on_create = True
        with pytest.raises(TPUMounterError):
            _attach(chaos)
        assert chaos.rig.journal.backlog() == 0
        assert chaos.restart_worker() == {}
        assert_invariants(chaos.rig, set(), max_attached_events=0)
    finally:
        chaos.close()


# -- retry idempotency under faults (rid fencing + adoption) -------------------

def test_caller_retry_after_fault_burst_converges(fake_host):
    """A 503 burst DEEPER than the retry budget kills attempt 1 inside
    the allocation wait; the failure cleans up its slave pods, and the
    caller's retry with the same request id converges on exactly one
    reservation set — no double allocation, no leak."""
    plan = FaultPlan("burst_outlives_retries", [
        # the fake client retries 4x per call; LIST #3 (the allocation
        # wait's seed LIST) eats all 4 failures and dies for real
        Fault(op="LIST", resource="pods", status=503, times=4, after=2)])
    chaos = ChaosRig(fake_host, n_chips=4, plan=plan)
    try:
        with pytest.raises(TPUMounterError):
            _attach(chaos)
        # the failed attempt rolled its slave pods back before raising
        assert chaos.rig.sim.slave_pods() == []
        # caller retries once the burst is over (same rid)
        outcome = _attach(chaos)
        assert outcome.result == consts.AddResult.SUCCESS
        assert len(chaos.rig.sim.slave_pods()) == 1
        assert_invariants(chaos.rig, ALL_CHIPS)
        assert len(chaos.injector.fired) == 4
    finally:
        chaos.close()


def test_ambiguous_create_failure_is_never_blindly_replayed(fake_host):
    """A 503 on a slave-pod POST may mean the apiserver persisted the pod
    before failing; blindly replaying the POST would 409 against our own
    object and the cleanup would miss it. The stricter non-idempotent
    classifier surfaces the failure instead (exactly ONE POST attempt),
    the attach rolls back cleanly, and the caller's request-id retry is
    the safe convergence path."""
    plan = FaultPlan("ambiguous_create_503", [
        Fault(op="POST", resource="pods", status=503, times=1)])
    chaos = ChaosRig(fake_host, n_chips=4, plan=plan)
    try:
        with pytest.raises(TPUMounterError):
            _attach(chaos)
        assert len(chaos.injector.fired) == 1      # no blind POST replay
        assert chaos.rig.sim.slave_pods() == []    # clean rollback
        outcome = _attach(chaos)                   # rid retry converges
        assert outcome.result == consts.AddResult.SUCCESS
        assert_invariants(chaos.rig, ALL_CHIPS)
    finally:
        chaos.close()


# -- gateway: per-worker circuit breaker + 429 mapping -------------------------

class _UnavailableError(Exception):
    pass


def _gateway_with_flaky_worker(worker):
    """A MasterGateway whose worker-client factory returns ``worker``."""
    from gpumounter_tpu.k8s.client import FakeKubeClient
    from gpumounter_tpu.master.discovery import WorkerDirectory
    from gpumounter_tpu.master.gateway import MasterGateway
    from gpumounter_tpu.testing.sim import make_target_pod, worker_pod
    from gpumounter_tpu.utils.retry import RetryPolicy
    kube = FakeKubeClient()
    kube.put_pod(worker_pod("node-a", "10.0.0.5"))
    kube.put_pod(make_target_pod())
    gateway = MasterGateway(kube, WorkerDirectory(kube),
                            worker_client_factory=lambda target: worker)
    gateway.rpc_retry_policy = RetryPolicy(max_attempts=2,
                                           base_delay_s=0.001,
                                           max_delay_s=0.001,
                                           deadline_s=5.0, jitter=0.0)
    gateway.breaker_failure_threshold = 2
    gateway.breaker_reset_timeout_s = 0.05
    return gateway


class _FlakyWorker:
    """Scriptable worker client: raises UNAVAILABLE ``down`` times, then
    answers SUCCESS."""

    def __init__(self, down):
        import grpc

        class Unavailable(grpc.RpcError):
            def code(self):
                return grpc.StatusCode.UNAVAILABLE

            def details(self):
                return "worker down"
        self._exc = Unavailable
        self.down = down
        self.calls = 0

    def add_tpu(self, *args, **kwargs):
        self.calls += 1
        if self.calls <= self.down:
            raise self._exc()

        class Resp:
            result = int(consts.AddResult.SUCCESS)
            device_ids = ["0"]
            device_paths = ["/dev/accel0"]
        return Resp()

    def close(self):
        pass


ADD_PATH = "/addtpu/namespace/default/pod/workload/tpu/1/isEntireMount/false"


def test_gateway_breaker_opens_to_429_with_retry_after_then_recovers():
    import time as time_mod
    worker = _FlakyWorker(down=10**9)
    gateway = _gateway_with_flaky_worker(worker)

    # request 1: two UNAVAILABLE attempts reach the threshold (2) — the
    # request itself still reports the worker error
    status, payload = gateway.handle("GET", ADD_PATH)
    assert status == 502
    assert payload["result"] == "UNAVAILABLE"
    # request 2: the breaker is open — fail fast, 429 + Retry-After
    status, payload = gateway.handle("GET", ADD_PATH)
    assert status == 429
    assert payload["result"] == "WorkerCircuitOpen"
    assert payload["retry_after_s"] > 0
    calls_while_open = worker.calls

    # open circuit: the dead worker is NOT dialed again
    status, _ = gateway.handle("GET", ADD_PATH)
    assert status == 429
    assert worker.calls == calls_while_open

    # worker recovers; after the reset timeout the half-open probe closes
    # the circuit and traffic flows again
    worker.down = worker.calls
    time_mod.sleep(0.06)
    status, payload = gateway.handle("GET", ADD_PATH)
    assert status == 200
    assert payload["result"] == "SUCCESS"
    status, _ = gateway.handle("GET", ADD_PATH)
    assert status == 200


def test_gateway_unavailable_retry_recovers_without_opening():
    """One blip, then healthy: the in-request retry absorbs it and the
    breaker stays closed."""
    worker = _FlakyWorker(down=1)
    gateway = _gateway_with_flaky_worker(worker)
    status, payload = gateway.handle("GET", ADD_PATH)
    assert status == 200
    assert payload["result"] == "SUCCESS"
    assert worker.calls == 2


def test_gateway_hung_worker_opens_breaker():
    """DEADLINE_EXCEEDED proves nothing about liveness and ate a gateway
    thread for the full deadline — it must count as breaker failure, or a
    hung-but-accepting worker starves the thread pool forever."""
    import grpc

    class Hung(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.DEADLINE_EXCEEDED

        def details(self):
            return "deadline exceeded"

    class HungWorker:
        def add_tpu(self, *args, **kwargs):
            raise Hung()

        def close(self):
            pass
    gateway = _gateway_with_flaky_worker(HungWorker())
    for _ in range(2):                   # threshold is 2
        status, payload = gateway.handle("GET", ADD_PATH)
        assert status == 504
        assert payload["result"] == "DEADLINE_EXCEEDED"
    status, payload = gateway.handle("GET", ADD_PATH)
    assert status == 429
    assert payload["result"] == "WorkerCircuitOpen"


def test_gateway_half_open_probe_survives_non_grpc_error():
    """A ValueError mid-probe (version-skewed worker enum) must not leak
    the half-open probe slot — the worker ANSWERED, so the circuit
    closes and traffic keeps flowing."""
    import time as time_mod

    class SkewedWorker:
        def __init__(self):
            self.calls = 0

        def add_tpu(self, *args, **kwargs):
            self.calls += 1

            class Resp:
                result = 99              # unknown enum value → ValueError
                device_ids = []
                device_paths = []
            return Resp()

        def close(self):
            pass
    worker = _FlakyWorker(down=10**9)
    gateway = _gateway_with_flaky_worker(worker)
    gateway.handle("GET", ADD_PATH)              # opens the breaker (2 fails)
    assert gateway.handle("GET", ADD_PATH)[0] == 429
    # swap in a worker that answers, but with a bogus enum
    skewed = SkewedWorker()
    gateway._worker_client_factory = lambda target: skewed
    gateway._drop_client("10.0.0.5:1200")
    time_mod.sleep(0.06)                         # past reset timeout
    status, payload = gateway.handle("GET", ADD_PATH)   # the probe
    assert status == 502 and payload["result"] == "UnknownWorkerResult"
    # the probe slot was NOT leaked: the next request goes through
    # (breaker closed), it does not 429
    status, payload = gateway.handle("GET", ADD_PATH)
    assert status == 502 and payload["result"] == "UnknownWorkerResult"
    assert skewed.calls == 2


def test_gateway_maps_resource_exhausted_to_429():
    import grpc

    class Exhausted(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.RESOURCE_EXHAUSTED

        def details(self):
            return "worker saturated"

    class SaturatedWorker:
        def add_tpu(self, *args, **kwargs):
            raise Exhausted()

        def close(self):
            pass
    gateway = _gateway_with_flaky_worker(SaturatedWorker())
    status, payload = gateway.handle("GET", ADD_PATH)
    assert status == 429
    assert payload["result"] == "RESOURCE_EXHAUSTED"
    assert payload["retry_after_s"] > 0


# -- real REST client against the HTTP facade under drops ----------------------

def test_rest_client_rides_out_http_connection_drops(tmp_path):
    """The production REST client against the HTTP apiserver facade with
    injected TCP connection drops: the retry layer classifies the torn
    connections and converges."""
    from gpumounter_tpu.k8s.client import FakeKubeClient, KubeconfigKubeClient
    from gpumounter_tpu.testing.chaos import FaultInjector
    from gpumounter_tpu.testing.http_apiserver import (HttpApiserver,
                                                       write_kubeconfig)
    from gpumounter_tpu.testing.sim import make_target_pod
    from gpumounter_tpu.utils.retry import RetryPolicy
    kube = FakeKubeClient()
    kube.put_pod(make_target_pod())
    apiserver = HttpApiserver(kube)
    try:
        apiserver.faults = FaultInjector([
            Fault(op="GET", resource="pods", drop=True, times=2)])
        cfg = write_kubeconfig(str(tmp_path / "kubeconfig"), apiserver.base)
        client = KubeconfigKubeClient(cfg)
        client.retry_policy = RetryPolicy(max_attempts=4,
                                          base_delay_s=0.01,
                                          max_delay_s=0.05, deadline_s=5.0,
                                          jitter=0.0)
        pod = client.get_pod("default", "workload")
        assert pod["metadata"]["name"] == "workload"
        assert len(apiserver.faults.fired) == 2
    finally:
        apiserver.close()


def test_journalz_served_on_worker_health_port(fake_host):
    """GET /journalz alongside /poolz and /tracez: backlog + replay
    outcomes visible to operators."""
    import json
    import urllib.request

    from gpumounter_tpu.worker.main import _HealthHandler, \
        start_health_server
    chaos = ChaosRig(fake_host, n_chips=4)
    server = None
    try:
        chaos.arm_crash("before_commit")
        with pytest.raises(WorkerCrash):
            _attach(chaos)
        _HealthHandler.journal = chaos.rig.journal
        server = start_health_server(0)
        url = f"http://127.0.0.1:{server.server_port}/journalz"
        with urllib.request.urlopen(url, timeout=5) as resp:
            payload = json.loads(resp.read())
        assert payload["backlog"] == 1
        assert payload["incomplete"][0]["pod"] == "workload"
        assert payload["incomplete"][0]["state"] == "intent"

        chaos.restart_worker()
        _HealthHandler.journal = chaos.rig.journal
        with urllib.request.urlopen(url, timeout=5) as resp:
            payload = json.loads(resp.read())
        assert payload["backlog"] == 0
        assert payload["replays"]["completed"] >= 1
    finally:
        _HealthHandler.journal = None
        if server is not None:
            server.shutdown()
        chaos.close()


def test_fault_free_path_adds_no_retries_or_extra_round_trips(fake_host):
    """The bench criterion, pinned as a test: with no faults injected, an
    attach performs ZERO retry attempts and exactly as many apiserver/
    kubelet round-trips as the one-shot era — the retry layer only exists
    once a call has already failed."""
    from gpumounter_tpu.utils.metrics import REGISTRY
    chaos = ChaosRig(fake_host, n_chips=4)
    try:
        before = {
            target: REGISTRY.retry_attempts.value(target=target)
            for target in ("apiserver", "kubelet", "worker_rpc", "watch")}
        kubelet_lists = chaos.rig.sim.podresources.list_calls
        assert _attach(chaos).result == consts.AddResult.SUCCESS
        for target, value in before.items():
            assert REGISTRY.retry_attempts.value(target=target) == value, \
                f"fault-free attach burned a {target} retry"
        # kubelet round-trips per attach unchanged (O(1), round-2 VERDICT)
        assert chaos.rig.sim.podresources.list_calls - kubelet_lists <= 3
    finally:
        chaos.close()


# -- informer in the loop: the shared cache weakens no invariant ---------------
# (ISSUE 4: the warm/cold attach paths now read pods from the shared
# list-watch cache; the same fault matrix contracts must hold when the
# informer's stream dies, hangs, or serves stale data mid-attach.)


def test_informer_attach_converges_when_watch_dies_mid_attach(fake_host):
    """The informer's ONE stream is now the allocation wait's event
    source: kill it repeatedly mid-attach (beyond the client's resume
    budget, forcing re-LIST resyncs) and the attach must still converge
    with every invariant intact."""
    plan = FaultPlan("informer_watch_death", [
        Fault(op="WATCH", resource="pods", drop=True, times=6)])
    chaos = ChaosRig(fake_host, n_chips=4, plan=plan, informer=True,
                     schedule_delay_s=0.15)
    try:
        outcome = _attach(chaos)
        assert outcome.result == consts.AddResult.SUCCESS
        assert sorted(c.uuid for c in outcome.chips) == sorted(ALL_CHIPS)
        assert_invariants(chaos.rig, ALL_CHIPS)
        assert chaos.injector.fired, "plan never bit — proves nothing"
    finally:
        chaos.close()


def test_informer_attach_converges_when_watch_hangs(fake_host):
    plan = FaultPlan("informer_watch_hang", [
        Fault(op="WATCH", resource="pods", latency_s=0.3, times=2)])
    chaos = ChaosRig(fake_host, n_chips=4, plan=plan, informer=True,
                     schedule_delay_s=0.15)
    try:
        assert _attach(chaos).result == consts.AddResult.SUCCESS
        assert_invariants(chaos.rig, ALL_CHIPS)
        assert chaos.injector.fired
    finally:
        chaos.close()


def test_warm_attach_survives_total_list_outage(fake_host):
    """The point of the cache, stated as chaos: with the informer + warm
    pool wired, an apiserver that 503s EVERY LIST cannot touch the warm
    attach path — zero LISTs are issued, so the outage plan never even
    fires."""
    chaos = ChaosRig(fake_host, n_chips=4, informer=True,
                     warm_pool={"entire:4": 1})
    try:
        chaos.rig.fill_warm_pool()
        chaos.install(FaultPlan("lists_down", [
            Fault(op="LIST", resource="pods", status=503, times=50)]))
        outcome = _attach(chaos)
        assert outcome.result == consts.AddResult.SUCCESS
        assert outcome.pool_hits == 1
        lists_fired = [f for f in chaos.injector.fired if f[0] == "LIST"]
        assert lists_fired == [], \
            f"warm attach issued apiserver LISTs: {lists_fired}"
        # outage over: the invariant check itself LISTs the fake directly
        chaos.rig.sim.kube.faults = None
        assert_invariants(chaos.rig, ALL_CHIPS)
    finally:
        chaos.close()


def test_stale_cache_view_cannot_double_adopt(fake_host, monkeypatch):
    """No stale-read double-attach: even when the pool's warm view is
    arbitrarily stale (exactly what an informer cache lagging an adoption
    event would serve), the resourceVersion-guarded adoption patch loses
    cleanly (409) and the second attach falls back cold — two owners can
    never share a slave pod."""
    chaos = ChaosRig(fake_host, n_chips=8, informer=True,
                     warm_pool={"entire:4": 1})
    rig = chaos.rig
    try:
        rig.fill_warm_pool()
        stale_view = [dict(p, metadata=dict(p["metadata"]))
                      for p in rig.pool._list_warm()]
        assert _attach(chaos, rid="owner-a").result \
            == consts.AddResult.SUCCESS
        # second owner; its claim sees the pre-adoption (stale) view
        from gpumounter_tpu.testing.sim import make_target_pod
        pod_b = make_target_pod(name="workload-b", uid="uid-b",
                                node=rig.sim.node)
        rig.sim.kube.put_pod(pod_b)
        rig.provision_container(pod_b)
        monkeypatch.setattr(rig.pool, "_list_warm", lambda: stale_view)
        out_b = rig.service.add_tpu("workload-b", "default", 4, True,
                                    request_id="owner-b")
        assert out_b.result == consts.AddResult.SUCCESS
        assert out_b.pool_hits == 0          # the stale claim lost its 409
        # disjoint slave sets: no chip serves two owners
        from gpumounter_tpu.k8s import objects
        owners = {}
        for slave in rig.sim.slave_pods():
            owner = objects.labels(slave).get(consts.OWNER_POD_LABEL_KEY)
            owners.setdefault(owner, set()).add(objects.name(slave))
        assert set(owners) == {"workload", "workload-b"}
        assert not (owners["workload"] & owners["workload-b"])
    finally:
        chaos.close()


def test_informer_crash_replay_still_converges(fake_host):
    """Crash-restart with the informer wired: the journal replay's reads
    go through the cache and the attach still completes exactly once."""
    chaos = ChaosRig(fake_host, n_chips=4, informer=True)
    try:
        chaos.arm_crash("before_commit")
        with pytest.raises(WorkerCrash):
            _attach(chaos)
        outcomes = chaos.restart_worker()
        assert outcomes == {"completed": 1}
        assert_invariants(chaos.rig, ALL_CHIPS, max_attached_events=1)
    finally:
        chaos.close()
