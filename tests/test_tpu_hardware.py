"""TPU-gated hardware tests.

The rest of the suite pins JAX to a virtual 8-device CPU mesh
(``conftest.py``); these tests instead run the real-chip selftest
(:mod:`gpumounter_tpu.jaxcheck.tpu_selftest`) in a subprocess with a clean
environment, so a live TPU backend (if any) is exercised without
contaminating — or being contaminated by — the CPU-pinned test process.

Skips cleanly when no TPU backend initialises (selftest exit code 3), so the
suite stays green on CPU-only CI while producing hardware evidence on the
bench host. This is the framework's analog of the reference's real-GPU
QuickStart verification (``docs/guide/QuickStart.md:42-97``).
"""

import pytest

from gpumounter_tpu.jaxcheck import tpu_selftest


@pytest.fixture(scope="module")
def selftest_report():
    rc, report, error = tpu_selftest.run_in_subprocess()
    if rc == tpu_selftest.EXIT_NO_TPU:
        pytest.skip("no TPU backend on this host")
    assert report is not None, error
    return report


def test_tpu_backend_enumerates(selftest_report):
    dev = selftest_report["devices"]
    assert dev["backend"] == "tpu"
    assert dev["device_count"] >= 1


def test_tpu_collectives(selftest_report):
    assert selftest_report["collectives"]["ok"], selftest_report["collectives"]


def test_tpu_training_loss_decreases(selftest_report):
    tr = selftest_report["training"]
    assert tr["ok"], tr
    assert tr["final_loss"] < tr["first_loss"]
    assert tr["step_ms"] > 0


def test_tpu_mfu_is_reported_and_plausible(selftest_report):
    """The MXU-sized bf16 perf check (r2 VERDICT missing #1): an analytic
    FLOPs count, a net step time, and an MFU in (0, 1] against the chip's
    published peak. The tight floors are v5e regression guards (round-4
    measured ~0.62-0.65 primary / ~0.75 tuned ON v5e); other generations,
    where these configs weren't tuned, only get the generic sanity floor."""
    perf = selftest_report["perf"]
    assert perf["ok"], perf
    assert perf["config"]["dtype"] == "bfloat16"
    assert perf["model_tflops_per_step"] > 1.0      # genuinely MXU-sized
    assert perf["train_step_ms"] > 0
    if perf["peak_bf16_tflops"] is not None:
        assert 0.2 < perf["mfu"] <= 1.0, perf
        if "v5 lite" in perf["device_kind"].lower():
            # round-5 regression floors: flash-kernel primary measured
            # 0.73-0.74 on v5e; the tuned 8x-MLP entry no longer clearly
            # exceeds it (both ride the same kernels), so both get the
            # same floor instead of an ordering claim.
            assert 0.65 < perf["mfu"] <= 1.0, perf
            assert perf["tuned"]["ok"], perf
            assert 0.65 < perf["tuned"]["mfu"] <= 1.0, perf
            # the kernel's edge over stock XLA attention stays measured
            if perf.get("xla_attention", {}).get("ok"):
                assert perf["mfu"] > perf["xla_attention"]["mfu"], perf


def test_tpu_pallas_parity_pinned_precision(selftest_report):
    """The fused MXU kernel matches the einsum reference AND a float64
    oracle under jax.default_matmul_precision("highest") — on the real MXU,
    not interpret mode."""
    pp = selftest_report["pallas_parity"]
    assert pp["ok"], pp
    assert pp["err_pallas_vs_oracle"] < pp["tol"]
    assert pp["err_pallas_vs_einsum"] < pp["tol"]


def test_tpu_backend_reinit_no_wedge(selftest_report):
    """probe.reinitialize_backend() against live libtpu, REPEATEDLY (the
    wait_for_devices poll loop re-inits every 2 s): every cycle must
    re-enumerate the same device count and still run compute — a wedge
    after the Nth re-init is the plausible field failure (round-4
    VERDICT weak #5)."""
    br = selftest_report["backend_reinit"]
    assert br["ok"], br
    assert br["devices_before"] == br["devices_after"]
    assert br["compute_ok"]
    assert br["cycles"] >= 5, br


def test_tpu_long_context_training(selftest_report):
    """Round-4 VERDICT next #1 done-criterion: TRAINING at seq 4096 and
    8192 on the flagship dims runs through the trainable pallas flash
    attention with finite loss and a reported MFU, while autodiff through
    XLA full attention at those lengths either measurably OOMs or was
    predicted (arithmetically) to exceed HBM several-fold."""
    lc = selftest_report["long_context"]
    assert lc["ok"], lc
    by_seq = {r["seq"]: r for r in lc["rows"]}
    for seq in (4096, 8192, 16384):
        fl = by_seq[seq]["flash"]
        assert fl["ok"], fl
        assert fl["train_step_ms"] > 0
        assert 0 < fl["mfu"] <= 1.0
    xla = {r["seq"]: r for r in lc["xla_full_attention"]}
    for seq in (4096, 8192, 16384):
        res = xla[seq]["result"]
        # ran (big-HBM chip) or OOMed (measured or predicted) — but the
        # flash path must run either way, which the loop above asserted
        assert res == "ran" or str(res).startswith("OOM"), xla[seq]
        if res == "ran":
            # when XLA does squeeze through, flash must actually beat it
            # (round-4 measured 1.56x at seq 4096 on v5e)
            assert (by_seq[seq]["flash"]["train_step_ms"]
                    < xla[seq]["train_step_ms"]), (by_seq[seq], xla[seq])


def test_tpu_roofline_explains_step_time(selftest_report):
    """The flagship MFU figure must be accompanied by a decomposition that
    accounts for most of the step: GEMM standalone times + attention core
    + optimizer should explain the majority of the measured step, and the
    measured MFU should sit within ~15% of the matmul-only ceiling (the
    step cannot beat its own GEMMs run standalone)."""
    rf = selftest_report["roofline"]
    assert rf["ok"], rf
    assert rf["explained_fraction"] > 0.7, rf
    # Structural claims only — the standalone timings carry chain-link
    # overhead and host-load noise (measured ceiling ranged 0.54-0.64
    # across runs of round 5), so exact measured-vs-ceiling ordering is
    # not assertable on a shared chip. What must hold: the decomposition
    # exists for every GEMM shape, and GEMM time dominates the step (the
    # basis of the "MFU is GEMM-floor-bound" argument).
    assert set(rf["gemms"]) == {"qkv_proj", "out_proj", "mlp_in",
                                "mlp_out", "lm_head"}, rf
    assert rf["matmul_pred_ms"] >= 0.5 * rf["measured_step_ms"], rf
    if rf["matmul_ceiling_mfu"] is not None:
        assert 0.3 < rf["matmul_ceiling_mfu"] <= 1.0, rf


def test_tpu_drain_cycle_loss_continuity(selftest_report):
    """BASELINE config 4 on hardware: drain -> backend re-init (the
    detach/reattach window) -> restore -> the next step's loss equals the
    uninterrupted run's."""
    dc = selftest_report["drain_cycle"]
    assert dc["ok"], dc
    assert dc["abs_err"] < 1e-3
    assert dc["drain_restore_s"] > 0


def test_tpu_pallas_kernel_wins_at_long_sequence(selftest_report):
    """The repo's pallas flash block kernel must beat XLA's fused
    attention at seq >= 4096 (shorter is measurement noise), and run seq
    8192. Whether XLA is attempted at 8192 depends on this chip's HBM
    (predicted-OOM skip on small chips, real attempt on big ones) — either
    way pallas must run it; if XLA was attempted and ran, pallas must not
    lose there."""
    ak = selftest_report["attention_kernels"]
    assert ak["ok"], ak     # ok=False on any "err:" non-result (perf.py)
    by_seq = {r["seq"]: r for r in ak["rows"]}
    xla4k = by_seq[4096]["xla_ms"]
    if isinstance(xla4k, float):
        assert by_seq[4096]["pallas_ms"] < xla4k
    else:                   # small-HBM chip: XLA already out of memory here
        assert str(xla4k).startswith("OOM")
    assert isinstance(by_seq[8192]["pallas_ms"], float)
    xla8k = by_seq[8192]["xla_ms"]
    if isinstance(xla8k, float):        # big-HBM chip: XLA ran
        assert by_seq[8192]["pallas_ms"] <= xla8k
    else:
        assert str(xla8k).startswith("OOM")
