"""Usage-sampler lint (AST-based, à la test_actuation_lint): sampling
must stay OFF the attach/detach hot path. The sampler owns its thread;
request threads may at most serve ALREADY-collected state (/utilz =
``snapshot()``). These lints pin that:

1. no hot-path module can even import ``collector.usage``;
2. the request-path methods of the mount service never touch a sampler;
3. the health handler serves ``snapshot()`` only — no ``sample_once``/
   ``update_status`` reachable from a health request thread;
4. the sampler ships ON by default (``TPU_USAGE=0`` reverts), with
   sampling driven exclusively by its own loop thread.
"""

import ast
import inspect

import gpumounter_tpu.actuation.mount as mount_mod
import gpumounter_tpu.allocator.allocator as allocator_mod
import gpumounter_tpu.collector.collector as collector_mod
import gpumounter_tpu.collector.usage as usage_mod
import gpumounter_tpu.worker.grpc_server as grpc_mod
import gpumounter_tpu.worker.service as service_mod

# Everything an AddTPU/RemoveTPU request thread executes.
HOT_PATH_MODULES = (service_mod, grpc_mod, allocator_mod, mount_mod,
                    collector_mod)


def _imports(tree: ast.AST) -> set[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            out |= {a.name for a in node.names}
        elif isinstance(node, ast.ImportFrom):
            out.add(node.module or "")
    return out


def test_no_hot_path_module_imports_the_sampler():
    offenders = []
    for module in HOT_PATH_MODULES:
        tree = ast.parse(inspect.getsource(module))
        hits = {name for name in _imports(tree) if "usage" in name}
        if hits:
            offenders.append(f"{module.__name__}: {sorted(hits)}")
    assert offenders == [], \
        f"sampler reachable from the hot path: {offenders}"


def test_request_path_methods_never_touch_a_sampler():
    """The mount service's request-path methods (everything a gRPC
    request thread runs) must not reference sampler state — sampling is
    the background thread's job, attribution reads are the sampler's
    calls INTO the service (attachment_owners), never the reverse."""
    source = inspect.getsource(service_mod.TPUMountService)
    tree = ast.parse("class _T:\n" + "\n".join(
        "    " + line for line in source.splitlines()))
    request_paths = {"add_tpu", "_add_tpu", "remove_tpu", "_remove_tpu",
                     "tpu_status", "node_status"}
    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef) \
                or node.name not in request_paths:
            continue
        for sub in ast.walk(node):
            name = (sub.attr if isinstance(sub, ast.Attribute)
                    else sub.id if isinstance(sub, ast.Name) else "")
            if name and ("sampler" in name or name == "sample_once"
                         or name == "usage"):
                offenders.append(f"{node.name}: {name}")
    assert offenders == [], \
        f"request path touches sampler state: {offenders}"


def test_health_handler_serves_snapshot_not_sampling():
    """GET /utilz answers already-collected state: the handler may call
    ``snapshot()`` but never ``sample_once``/``update_status`` — a
    scrape must not become a sampling pass on the request thread."""
    import gpumounter_tpu.worker.main as main_mod
    source = inspect.getsource(main_mod._HealthHandler)
    assert "sample_once" not in source
    assert "update_status" not in source
    assert ".snapshot()" in source      # the sanctioned read


def test_sampling_runs_only_from_the_loop_thread():
    """Inside collector/usage.py itself, ``sample_once`` is invoked from
    exactly one place: the sampler's own ``_run`` loop. Everything else
    (tests, bench) drives it explicitly from outside."""
    tree = ast.parse(inspect.getsource(usage_mod))
    callers = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "sample_once":
                    callers.append(node.name)
    assert callers == ["_run"], \
        f"sample_once called outside the loop thread: {callers}"


def test_usage_is_the_production_default():
    from gpumounter_tpu.utils.config import Settings
    assert Settings().usage_enabled is True
    assert Settings.from_env({}).usage_enabled is True
    assert Settings.from_env({"TPU_USAGE": "0"}).usage_enabled is False


def test_snapshot_performs_no_probe_or_inventory_reads():
    """The /utilz serving path (snapshot) must not probe devices or
    re-derive inventory — it renders the ring the loop filled."""
    source = inspect.getsource(usage_mod.ChipUsageSampler.snapshot)
    for forbidden in ("probe.sample", "update_status", "enumerate"):
        assert forbidden not in source, forbidden
