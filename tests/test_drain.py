"""Drain/restore round-trips (BASELINE config 4): live sharded training
state survives a backend re-initialisation — the in-process survival story
for detach + reattach."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from gpumounter_tpu.jaxcheck import drain as drain_lib
from gpumounter_tpu.jaxcheck import model as model_lib
from gpumounter_tpu.jaxcheck import train as train_lib

TINY = model_lib.ModelConfig(vocab=64, d_model=64, n_heads=8, n_layers=1,
                             d_ff=128)


def _trained_state(mesh, steps=2):
    state = train_lib.init_state(jax.random.PRNGKey(0), TINY, mesh)
    step = train_lib.make_train_step(TINY, mesh)
    tokens = train_lib.make_batch(jax.random.PRNGKey(1), 4, 32, TINY.vocab)
    for _ in range(steps):
        state, loss = step(state, tokens)
    return state, step, tokens, float(loss)


def test_drain_writes_checkpoint_and_returns_host_tree(tmp_path):
    mesh = model_lib.make_mesh(data=2, model=2)
    state, *_ = _trained_state(mesh)
    path = str(tmp_path / "ckpt" / "state.pkl")
    host = drain_lib.drain(state, path)
    assert os.path.exists(path)
    for leaf in jax.tree.leaves(host):
        assert isinstance(leaf, np.ndarray) or np.isscalar(leaf)


def test_restore_preserves_values_and_structure(tmp_path):
    mesh = model_lib.make_mesh(data=2, model=2)
    state, step, tokens, _ = _trained_state(mesh)
    path = str(tmp_path / "state.pkl")
    drain_lib.drain(state, path)
    restored = drain_lib.restore(path)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_training_continues_identically_after_drain_restore(tmp_path):
    mesh = model_lib.make_mesh(data=2, model=2)
    state, step, tokens, _ = _trained_state(mesh)

    # drain first: the jitted step donates its input state, so the live
    # buffers are consumed by the ground-truth step below (exactly the
    # ordering a real drain must respect)
    path = str(tmp_path / "state.pkl")
    drain_lib.drain(state, path)

    # ground truth: next loss without any drain
    _, expected_loss = step(state, tokens)

    restored = drain_lib.restore(path)
    # pytree type must survive (TrainState/optax structures, not raw dicts)
    assert isinstance(restored, train_lib.TrainState)
    _, resumed_loss = step(restored, tokens)
    assert abs(float(resumed_loss) - float(expected_loss)) < 1e-6


def test_restore_onto_explicit_shardings(tmp_path):
    mesh = model_lib.make_mesh(data=2, model=2)
    state, *_ = _trained_state(mesh)
    path = str(tmp_path / "state.pkl")
    drain_lib.drain(state.params, path)

    # "reattached" with a different topology: pure seq mesh
    new_mesh = model_lib.make_mesh()
    shardings = model_lib.param_shardings(new_mesh, TINY)
    params = drain_lib.restore(path, shardings)
    wqkv = params["layers"][0]["wqkv"]
    assert wqkv.sharding.mesh.shape == new_mesh.shape
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
