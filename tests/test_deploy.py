"""Deploy manifest sanity: parseable YAML, internally consistent names/
labels/ports, and consistent with the code's constants. The reference shipped
GPU_POOL_NAMESPACE=default while creating a gpu-pool namespace
(deploy/gpu-mounter-workers.yaml:33-34 vs namespace.yaml:4 — SURVEY.md §8);
this suite keeps that class of skew impossible here."""

import os
import stat
import subprocess

import yaml

from gpumounter_tpu.utils import consts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load(name):
    with open(os.path.join(REPO, "deploy", name)) as f:
        return list(yaml.safe_load_all(f))


def _master_docs():
    """(deployment, pdb) from the master manifest — two docs since the
    HA control plane shipped replicas: 2 behind a disruption budget."""
    docs = load("tpu-mounter-master.yaml")
    deployment = next(d for d in docs if d["kind"] == "Deployment")
    pdb = next(d for d in docs if d["kind"] == "PodDisruptionBudget")
    return deployment, pdb


def _production_manifests():
    # deploy/ top level = the production manifests deploy.sh applies;
    # subdirectories (e2e-kind/) are harness-specific overlays
    root = os.path.join(REPO, "deploy")
    return [n for n in os.listdir(root)
            if os.path.isfile(os.path.join(root, n))]


def test_all_manifests_parse():
    for name in _production_manifests():
        docs = load(name)
        assert docs and all(d for d in docs), name
    # the e2e overlay manifests must parse too
    for sub in ("e2e-kind",):
        subdir = os.path.join(REPO, "deploy", sub)
        for name in os.listdir(subdir):
            with open(os.path.join(subdir, name)) as f:
                docs = list(yaml.safe_load_all(f))
            assert docs and all(d for d in docs), f"{sub}/{name}" 


def test_pool_namespace_consistent_with_code():
    (ns,) = load("namespace.yaml")
    assert ns["metadata"]["name"] == consts.DEFAULT_POOL_NAMESPACE
    (worker,) = load("tpu-mounter-workers.yaml")
    env = {e["name"]: e.get("value")
           for e in worker["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env[consts.ENV_POOL_NAMESPACE] == consts.DEFAULT_POOL_NAMESPACE
    master, _ = _master_docs()
    menv = {e["name"]: e.get("value")
            for e in master["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert menv[consts.ENV_POOL_NAMESPACE] == consts.DEFAULT_POOL_NAMESPACE


def test_worker_labels_match_discovery_selector():
    (worker,) = load("tpu-mounter-workers.yaml")
    labels = worker["spec"]["template"]["metadata"]["labels"]
    key, _, value = consts.WORKER_LABEL_SELECTOR.partition("=")
    assert labels.get(key) == value
    assert worker["metadata"]["namespace"] == consts.WORKER_NAMESPACE


def test_worker_privileges_and_mounts():
    (worker,) = load("tpu-mounter-workers.yaml")
    spec = worker["spec"]["template"]["spec"]
    assert spec["hostPID"] is True
    container = spec["containers"][0]
    assert container["securityContext"]["privileged"] is True
    mounts = {m["mountPath"] for m in container["volumeMounts"]}
    # every host surface the actuation layer touches must be mounted
    assert {"/sys/fs/cgroup", "/dev", "/proc",
            "/var/lib/kubelet/pod-resources"} <= mounts
    ports = {p["containerPort"] for p in container["ports"]}
    assert consts.WORKER_GRPC_PORT in ports


def test_worker_lands_on_every_tpu_nodepool():
    """Affinity must be Exists on the accelerator label — a value-pinned
    nodeSelector would strand v4/v5p/v6e nodes, whose device shapes the
    enumerator supports (device/enumerator.py), with no worker."""
    (worker,) = load("tpu-mounter-workers.yaml")
    spec = worker["spec"]["template"]["spec"]
    assert "nodeSelector" not in spec
    terms = (spec["affinity"]["nodeAffinity"]
             ["requiredDuringSchedulingIgnoredDuringExecution"]
             ["nodeSelectorTerms"])
    exprs = [e for t in terms for e in t["matchExpressions"]]
    accel = [e for e in exprs
             if e["key"] == "cloud.google.com/gke-tpu-accelerator"]
    assert accel and all(e["operator"] == "Exists"
                         and "values" not in e for e in accel)
    # and the taint toleration stays, or no TPU node will admit it
    assert any(t.get("key") == "google.com/tpu"
               and t.get("operator") == "Exists"
               for t in spec["tolerations"])


def test_service_targets_master_port():
    (svc,) = load("tpu-mounter-svc.yaml")
    assert svc["spec"]["ports"][0]["targetPort"] == consts.MASTER_HTTP_PORT
    master, _ = _master_docs()
    mlabels = master["spec"]["template"]["metadata"]["labels"]
    for k, v in svc["spec"]["selector"].items():
        assert mlabels.get(k) == v


def test_master_ha_topology():
    """replicas: 2 is only safe with the FULL HA triple on (shards +
    election + store — docs/guide/HA.md); and two replicas need the
    spread + disruption guards or they share one failure domain."""
    master, pdb = _master_docs()
    assert master["spec"]["replicas"] == 2
    spec = master["spec"]["template"]["spec"]
    env = {e["name"]: e.get("value", e.get("valueFrom"))
           for e in spec["containers"][0]["env"]}
    assert env[consts.ENV_MASTER_SHARDS] == "2"
    assert env[consts.ENV_ELECTION] == "1"
    assert env[consts.ENV_INTENT_STORE] == "1"
    # replica identity = pod name; advertise URL = pod IP — both from the
    # downward API, so no two replicas can collide or advertise the VIP
    assert env[consts.ENV_REPLICA_ID]["fieldRef"]["fieldPath"] \
        == "metadata.name"
    assert "$(POD_IP)" in env[consts.ENV_ADVERTISE_URL]
    assert env["POD_IP"]["fieldRef"]["fieldPath"] == "status.podIP"
    terms = (spec["affinity"]["podAntiAffinity"]
             ["preferredDuringSchedulingIgnoredDuringExecution"])
    assert any(t["podAffinityTerm"]["topologyKey"]
               == "kubernetes.io/hostname" for t in terms)
    # the PDB must select these pods and keep one alive through drains
    assert pdb["spec"]["maxUnavailable"] == 1
    selector = pdb["spec"]["selector"]["matchLabels"]
    labels = master["spec"]["template"]["metadata"]["labels"]
    assert all(labels.get(k) == v for k, v in selector.items())


def test_rbac_grants_ha_configmap_access_pool_scoped_only():
    """The election locks and intent store live in pool-namespace
    ConfigMaps; the grant must be namespaced (a cluster-wide configmap
    write grant would let a compromised master poison any namespace)."""
    docs = load("rbac.yaml")
    for doc in docs:
        if doc["kind"] == "ClusterRole":
            for rule in doc["rules"]:
                assert "configmaps" not in rule["resources"]
    (role,) = [d for d in docs if d["kind"] == "Role"]
    cm_rules = [r for r in role["rules"]
                if "configmaps" in r["resources"]]
    assert cm_rules, "pool-namespace Role grants no configmap access"
    verbs = {v for r in cm_rules for v in r["verbs"]}
    assert {"get", "create", "patch", "delete"} <= verbs
    # patch (CAS merge) is the write primitive; update/replace would
    # bypass the resourceVersion discipline the store depends on
    assert "update" not in verbs


def test_rbac_is_not_cluster_admin():
    docs = load("rbac.yaml")
    for doc in docs:
        if doc["kind"] == "ClusterRoleBinding":
            assert doc["roleRef"]["name"] != "cluster-admin"
    # slave-pod writes only in the pool namespace
    roles = [d for d in docs if d["kind"] == "Role"]
    assert roles and all(
        r["metadata"]["namespace"] == consts.DEFAULT_POOL_NAMESPACE
        for r in roles)


def test_deploy_sh_is_executable_and_covers_manifests():
    path = os.path.join(REPO, "deploy.sh")
    assert os.stat(path).st_mode & stat.S_IXUSR
    content = open(path).read()
    for name in _production_manifests():
        assert f"deploy/{name}" in content, f"{name} missing from deploy.sh"
    rc = subprocess.run(["bash", "-n", path])
    assert rc.returncode == 0


# -- observability pack (deploy/observability/) -------------------------------

def _registry_metric_names():
    """Every metric family name the binaries export, from a fresh render
    (family headers render even with no series recorded)."""
    import re
    from gpumounter_tpu.utils.metrics import Registry
    text = Registry().render_text()
    return set(re.findall(r"^# TYPE (\S+)", text, re.M))


def _referenced_metrics(expr_text):
    """Metric names referenced in PromQL, with histogram suffixes folded
    back to the family name."""
    import re
    names = set()
    for tok in re.findall(r"\btpumounter_[a-z0-9_]+", expr_text):
        for suffix in ("_bucket", "_count", "_sum"):
            if tok.endswith(suffix):
                tok = tok[: -len(suffix)]
                break
        names.add(tok)
    return names


def test_grafana_dashboard_metrics_exist_in_code():
    import json
    with open(os.path.join(REPO, "deploy", "observability",
                           "grafana-dashboard.json")) as f:
        dash = json.load(f)
    exported = _registry_metric_names()
    exprs = [t["expr"] for p in dash["panels"]
             for t in p.get("targets", [])]
    assert exprs, "dashboard has no queries"
    for expr in exprs:
        refs = _referenced_metrics(expr)
        assert refs, f"no tpumounter metric in {expr!r}"
        missing = refs - exported
        assert not missing, f"dashboard references unexported {missing}"


def test_grafana_dashboard_panel_hygiene():
    """One axis per panel (no dual-axis overrides) and phase/result
    identity carried by legend labels, not color alone."""
    import json
    with open(os.path.join(REPO, "deploy", "observability",
                           "grafana-dashboard.json")) as f:
        dash = json.load(f)
    for panel in dash["panels"]:
        for target in panel.get("targets", []):
            if "by (le, phase)" in target["expr"]:
                assert "{{phase}}" in target.get("legendFormat", "")
            if "by (result)" in target["expr"]:
                assert "{{result}}" in target.get("legendFormat", "")
        # no per-series axis placement overrides = single axis
        overrides = panel.get("fieldConfig", {}).get("overrides", [])
        assert not any("axisPlacement" in str(o) for o in overrides), \
            panel["title"]


def test_prometheus_rules_parse_and_reference_real_metrics():
    with open(os.path.join(REPO, "deploy", "observability",
                           "prometheus-rules.yaml")) as f:
        doc = yaml.safe_load(f)
    rules = [r for g in doc["groups"] for r in g["rules"]]
    assert len(rules) >= 5
    exported = _registry_metric_names()
    for rule in rules:
        assert "alert" in rule and "expr" in rule
        assert rule["annotations"]["summary"]
        refs = _referenced_metrics(rule["expr"])
        assert refs, f"no tpumounter metric in {rule['alert']}"
        missing = refs - exported
        assert not missing, \
            f"{rule['alert']} references unexported {missing}"


def test_rules_exception_label_matches_service_semantics():
    """The EXCEPTION/POLICY_DENIED split the alerts rely on is the one the
    worker actually emits (service.py add_tpu finally block)."""
    with open(os.path.join(REPO, "deploy", "observability",
                           "prometheus-rules.yaml")) as f:
        text = f.read()
    assert 'result="EXCEPTION"' in text
    src = open(os.path.join(REPO, "gpumounter_tpu", "worker",
                            "service.py")).read()
    assert '"EXCEPTION"' in src and '"POLICY_DENIED"' in src


def test_grafana_dashboard_panels_use_datasource_variable():
    """The datasource dropdown must actually steer every panel."""
    import json
    with open(os.path.join(REPO, "deploy", "observability",
                           "grafana-dashboard.json")) as f:
        dash = json.load(f)
    names = [v["name"] for v in dash["templating"]["list"]]
    assert "datasource" in names
    for panel in dash["panels"]:
        assert panel.get("datasource", {}).get("uid") == "${datasource}", \
            panel["title"]
