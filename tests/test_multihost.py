"""Multi-host bring-up validation (BASELINE config 5, hardware-free half).

A v5p-16 slice spans hosts: after the slice attach, each pod sees only its
host's chips until ``jax.distributed.initialize`` federates them into one
world. These tests prove the probe's multi-process path end to end on one
machine: two subprocesses x 4 virtual CPU devices each (gloo cross-process
collectives) must federate to an 8-device world, agree on a cross-process
psum, and run the flagship sharded train step over the spanning mesh —
exactly what the two-pod recipe in docs/guide/QuickStart.md runs on a real
slice. (The reference has no multi-node story at all: its workers are
node-local and never coordinate, SURVEY.md §2 absence statement.)
"""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_probe_world(num_processes: int, cpu_devices: int,
                        expect: int, timeout_s: float = 420.0):
    """Run the probe CLI in ``num_processes`` subprocesses forming one JAX
    world; returns the parsed JSON report of each."""
    port = _free_port()
    env = dict(os.environ)
    # The probe pins the CPU backend itself (--cpu-devices); the suite's
    # XLA_FLAGS virtual-device pin must not fight jax_num_cpu_devices.
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "gpumounter_tpu.jaxcheck.probe",
             "--expect", str(expect),
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", str(num_processes),
             "--process-id", str(i),
             "--cpu-devices", str(cpu_devices)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO)
        for i in range(num_processes)
    ]
    reports = []
    for i, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"probe process {i} timed out after {timeout_s}s")
        assert proc.returncode == 0, (
            f"probe process {i} rc={proc.returncode}\n"
            f"stdout: {out}\nstderr tail: {err[-2000:]}")
        reports.append(json.loads(out.strip().splitlines()[-1]))
    return reports


def test_slice_attach_then_multihost_bringup(tmp_path):
    """BASELINE config 5 end to end: the control-plane half (all-or-
    nothing slice attach across two simulated TPU nodes via
    /addtpuslice) followed by the JAX half (the exact two-process
    bring-up each pod then runs: federate, cross-process collectives,
    sharded train step). SURVEY.md:99-104 makes the SECOND half the
    acceptance criterion — chips attached is not chips usable."""
    import urllib.request

    from gpumounter_tpu.testing.sim import MultiNodeStack
    from gpumounter_tpu.utils.config import HostPaths

    def host(i):
        base = tmp_path / f"node{i}"
        for sub in ("dev", "proc", "sys/fs/cgroup"):
            (base / sub).mkdir(parents=True)
        return HostPaths(dev_root=str(base / "dev"),
                         proc_root=str(base / "proc"),
                         sys_root=str(base / "sys"),
                         cgroup_root=str(base / "sys" / "fs" / "cgroup"),
                         kubelet_socket=str(base / "pr" / "kubelet.sock"))

    stack = MultiNodeStack([host(0), host(1)], n_chips=4)
    try:
        req = urllib.request.Request(
            f"{stack.base}/addtpuslice",
            data=json.dumps({
                "pods": [{"namespace": "default", "pod": "workload-0"},
                         {"namespace": "default", "pod": "workload-1"}],
                "tpusPerHost": 4}).encode(),
            method="POST")
        with urllib.request.urlopen(req) as resp:
            body = json.loads(resp.read())
        assert body["result"] == "SUCCESS", body
        assert all(p["result"] == "SUCCESS" for p in body["pods"]), body
    finally:
        stack.close()

    # the slice is attached; now the bring-up each pod runs (QuickStart
    # §7) — hardware-free stand-in: 4 virtual devices per "pod"
    reports = _launch_probe_world(num_processes=2, cpu_devices=4, expect=8)
    for report in reports:
        assert report["ok"], report
        assert report["devices"]["device_count"] == 8
        assert report["training"]["ok"], report["training"]


def test_two_process_world_federates_and_trains():
    reports = _launch_probe_world(num_processes=2, cpu_devices=4, expect=8)
    for i, report in enumerate(reports):
        assert report["ok"], report
        dev = report["devices"]
        assert dev["device_count"] == 8, dev
        assert dev["local_device_count"] == 4, dev
        assert dev["process_count"] == 2, dev
        assert dev["process_index"] == i, dev
        coll = report["collectives"]
        assert coll["ok"] and not coll["degenerate_single_device"], coll
        assert coll["n_devices"] == 8, coll
        # the flagship sharded train step ran over the spanning mesh
        tr = report["training"]
        assert tr["ok"], tr
        assert tr["mesh"] == {"data": 1, "seq": 8, "model": 1}, tr
