"""Leader-election suite (master/election.py): acquisition of free and
expired locks, renewal, CAS races producing exactly one winner, fencing
token bumps on takeover, local-validity decay without apiserver access,
demotion on observing a foreign holder, and the transition events +
metrics doctor/alerts consume."""

import time

from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.master.election import NullElection, ShardElection
from gpumounter_tpu.master.shardring import HAConfig
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.events import EVENTS
from gpumounter_tpu.utils.metrics import REGISTRY


def make_election(kube, replica, shards=1, url="", renew=0.5, ttl=1.5,
                  **hooks):
    ha = HAConfig(shards=shards, election=True, replica=replica,
                  advertise_url=url or f"http://{replica}:8080",
                  renew_interval_s=renew, lease_duration_s=ttl)
    return ShardElection(kube, ha, **hooks)


def test_acquires_free_shard_and_renews():
    kube = FakeKubeClient()
    acquired = []
    election = make_election(kube, "m0",
                             on_acquire=lambda s: acquired.append(s))
    election.tick()
    assert acquired == [0]
    assert election.is_leader(0) and election.token(0) == 1
    assert election.owned() == [0]
    # renewal pushes the deadline and keeps the fence stable
    election.tick()
    assert election.token(0) == 1
    assert REGISTRY.election_is_leader.value(shard="0") == 1
    snap = election.snapshot()
    assert snap["shards"]["0"]["holder"] == "m0"
    assert snap["shards"]["0"]["leader"] is True


def test_acquire_race_has_one_winner():
    kube = FakeKubeClient()
    a = make_election(kube, "m0")
    b = make_election(kube, "m1")
    a.tick()
    b.tick()
    assert a.is_leader(0) and not b.is_leader(0)
    # the loser's routing view names the winner
    assert b.leaders()[0]["holder"] == "m0"
    assert b.leaders()[0]["url"] == "http://m0:8080"


def test_dead_leader_fails_over_with_fence_bump():
    kube = FakeKubeClient()
    a = make_election(kube, "m0", ttl=0.2, renew=0.1)
    b = make_election(kube, "m1", ttl=0.2, renew=0.1,
                      url="http://m1:8080")
    a.tick()
    assert a.is_leader(0)
    b.tick()
    assert not b.is_leader(0)
    # m0 "dies": no more renews; its LOCAL validity decays too
    time.sleep(0.25)
    assert not a.is_leader(0), "a non-renewing holder must stop acting"
    lost = REGISTRY.election_transitions.value(shard="0", outcome="lost")
    b.tick()                          # observes the expired deadline
    assert b.is_leader(0)
    assert b.token(0) == 2, "takeover must bump the fencing token"
    # the zombie's next tick sees the foreign holder and demotes cleanly
    a.tick()
    assert not a.is_leader(0)
    assert REGISTRY.election_transitions.value(
        shard="0", outcome="lost") >= lost + 1


def test_lost_shard_fires_on_lose_hook_and_event():
    kube = FakeKubeClient()
    lost = []
    a = make_election(kube, "m0", ttl=0.2, renew=0.1,
                      on_lose=lambda s: lost.append(s))
    b = make_election(kube, "m1", ttl=0.2, renew=0.1)
    a.tick()
    time.sleep(0.25)
    b.tick()
    before = EVENTS.tail(256)
    a.tick()
    assert lost == [0]
    kinds = [e["kind"] for e in EVENTS.tail(256)[len(before) - 256:]]
    assert "election_lost" in [e["kind"] for e in EVENTS.tail(256)]
    assert "election_acquired" in kinds or "election_acquired" in \
        [e["kind"] for e in before]


def test_demote_on_fenced_write():
    kube = FakeKubeClient()
    lost = []
    a = make_election(kube, "m0", on_lose=lambda s: lost.append(s))
    a.tick()
    assert a.is_leader(0)
    a.demote(0, "fenced store write")
    assert not a.is_leader(0) and lost == [0]
    assert a.token(0) is None


def test_restart_within_own_ttl_resumes_without_fence_bump():
    kube = FakeKubeClient()
    a = make_election(kube, "m0")
    a.tick()
    assert a.token(0) == 1
    # same replica identity, fresh process (a Deployment restart): the
    # lock still names it, so it resumes instead of fencing itself out
    a2 = make_election(kube, "m0")
    a2.tick()
    assert a2.is_leader(0) and a2.token(0) == 1


def test_multi_shard_ownership_is_per_shard():
    kube = FakeKubeClient()
    a = make_election(kube, "m0", shards=2)
    b = make_election(kube, "m1", shards=2, ttl=1.5)
    a.tick()                      # grabs both free shards
    assert set(a.owned()) == {0, 1}
    b.tick()
    assert b.owned() == []
    # m0 releases nothing; only expiry hands shards over — b's view
    # still routes every shard to m0
    leaders = b.leaders()
    assert {leaders[s]["holder"] for s in (0, 1)} == {"m0"}


def test_null_election_owns_everything_with_no_traffic():
    kube = FakeKubeClient()
    null = NullElection(4)
    assert null.is_leader(3) and null.token(0) is None
    assert null.owned() == [0, 1, 2, 3]
    null.tick()
    null.start()
    null.stop()
    assert kube.cm_calls == 0
    assert null.snapshot() == {"enabled": False, "shards": 4}


def test_election_loop_start_stop():
    kube = FakeKubeClient()
    a = make_election(kube, "m0", renew=0.05, ttl=0.3)
    a.start()
    deadline = time.monotonic() + 5.0
    while not a.is_leader(0):
        assert time.monotonic() < deadline
        time.sleep(0.01)
    a.stop()
    # stopping does NOT release the lock — it expires, like a crash
    cm = kube.get_config_map(consts.DEFAULT_POOL_NAMESPACE,
                             a.lock_name(0))
    holder = cm["metadata"]["annotations"]["tpumounter.io/holder"]
    assert holder == "m0"


def test_deleted_lock_object_cannot_livelock_below_the_store_fence():
    """Review fix: an operator deleting the lock ConfigMap restarts
    lock fences at 1 while the STORE still records a higher fence; the
    refused fence is noted and the next acquisition (and even a resume
    renew of a stale lock) clears it instead of livelocking
    acquire → fenced write → demote forever."""
    kube = FakeKubeClient()
    election = make_election(kube, "m0")
    election.tick()
    assert election.token(0) == 1
    # the store refused a write with recorded fence 7 (the broker's
    # _on_fenced path calls exactly this before demoting)
    election.note_fence(0, 7)
    election.demote(0, "fenced store write")
    assert not election.is_leader(0)
    # the lock still NAMES m0 (demotion is local): the resume-renew
    # must bump past the floor, not resume the dead token
    election.tick()
    assert election.is_leader(0)
    assert election.token(0) == 8
    # and a fresh lock object (deleted + recreated) also clears it
    kube.delete_config_map(consts.DEFAULT_POOL_NAMESPACE,
                           election.lock_name(0))
    election.note_fence(0, 11)
    election.demote(0, "fenced again")
    election.tick()
    assert election.is_leader(0)
    assert election.token(0) == 12


def test_validity_anchored_at_tick_start_not_patch_completion():
    """Review fix: the lock's advertised deadline is tick-start + TTL,
    so local validity must anchor there too — anchoring after the
    apiserver round-trip would keep is_leader() True past the deadline
    a peer is entitled to take over at (admission overlap)."""
    from gpumounter_tpu.testing.chaos import Fault, FaultInjector
    kube = FakeKubeClient()
    election = make_election(kube, "m0", ttl=1.5)
    rtt = 0.25
    kube.faults = FaultInjector(
        [Fault(op="GET", resource="configmaps", latency_s=rtt, times=50)])
    t0 = time.monotonic()
    election.tick()
    kube.faults = None
    held = election._held[0]
    # validity ends within TTL of TICK START (+ scheduling slack), not
    # TTL past the slow round-trip's completion
    assert held.valid_until <= t0 + 1.5 + 0.05, \
        "leadership validity extends past the advertised lock deadline"
