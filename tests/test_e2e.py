"""End-to-end harness (BASELINE config 1): a curl-equivalent HTTP request to
the real master gateway, through real gRPC to the worker service, through the
real allocator against a scripted scheduler, down to real cgroup-v1 file
writes and device-node creation in a fixture container root.

This exercises every layer of SURVEY.md §3.2/§3.3's call stacks except the
kube-apiserver (FakeKubeClient) and real mknod privileges (fake device
nodes); the fake-kubelet gRPC unix socket variant lives in
tests/test_collector.py.
"""

import json
import os
import urllib.request

import pytest

from tests.helpers import LiveStack, WorkerRig


@pytest.fixture
def live_stack(fake_host):
    """Everything live on localhost: HTTP master + gRPC worker, with the
    collector reading a real unix-socket kubelet."""
    stack = LiveStack(WorkerRig(fake_host, use_kubelet_socket=True))
    yield stack.rig, stack.base
    stack.close()


def _get(url):
    try:
        resp = urllib.request.urlopen(url)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(url, data: bytes):
    req = urllib.request.Request(url, data=data, method="POST")
    try:
        resp = urllib.request.urlopen(req)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_full_attach_detach_over_http(live_stack):
    rig, base = live_stack

    # attach 4 chips as an entire mount — the QuickStart flow
    status, body = _get(
        f"{base}/addtpu/namespace/default/pod/workload/tpu/4"
        "/isEntireMount/true")
    assert status == 200
    assert body["result"] == "SUCCESS"
    assert sorted(body["device_paths"]) == [
        "/dev/accel0", "/dev/accel1", "/dev/accel2", "/dev/accel3"]

    # observable side effects on the "node"
    assert len(rig.sim.slave_pods()) == 1
    assert os.path.exists(os.path.join(rig.cgroup_dir, "devices.allow"))
    assert len(rig.actuator.created) == 4

    # detach everything
    status, body = _post(
        f"{base}/removetpu/namespace/default/pod/workload/force/false",
        json.dumps({"uuids": body["device_ids"]}).encode())
    assert status == 200
    assert body["result"] == "SUCCESS"
    assert rig.sim.slave_pods() == []
    assert rig.sim.podresources.assignments == {}
    assert len(rig.actuator.removed) == 4

    # node is reusable immediately
    status, body = _get(
        f"{base}/addtpu/namespace/default/pod/workload/tpu/1"
        "/isEntireMount/false")
    assert status == 200


def test_metrics_exposed_over_http(live_stack):
    rig, base = live_stack
    _get(f"{base}/addtpu/namespace/default/pod/workload/tpu/1"
         "/isEntireMount/false")
    resp = urllib.request.urlopen(f"{base}/metrics")
    text = resp.read().decode()
    assert "tpumounter_attach_seconds_bucket" in text
    assert "tpumounter_attach_total" in text


def test_healthz(live_stack):
    _, base = live_stack
    status, body = _get(f"{base}/healthz")
    assert status == 200 and body["status"] == "ok"
