"""Indexed waiter wakeup (master/waiterindex.py): the selection order is
the broker's fairness CONTRACT, so the index must be provably the same
scheduler as the linear scan it replaces — pinned here by a randomized
equivalence drive (1k park/wake/timeout/preempt interleavings against a
brute-force reference), plus the perf property the index exists for:
a capacity signal's evaluation cost scales with the signalling node's
own candidates, not total parked waiters."""

import random
import threading

from gpumounter_tpu.master.waiterindex import WaiterQueue, _rank
from gpumounter_tpu.utils import consts


class W:
    """The selection-relevant surface of admission._Waiter."""

    _counter = [0]

    def __init__(self, tenant="t0", priority="normal", chips=1,
                 node="node-a", gang=False):
        self.tenant = tenant
        self.priority = priority
        self.chips = chips
        self.node = "" if gang else node
        self.gang = gang
        W._counter[0] += 1
        self.enqueued_at = float(W._counter[0])
        self.tried_gen = 0
        self.event = threading.Event()

    def __repr__(self):
        return (f"W({self.tenant},{self.priority},c{self.chips},"
                f"{self.node or 'gang'},@{self.enqueued_at})")


def reference_select(ordered, gen, node=None, chips=0, usage=None,
                     quotas=None):
    """Brute force over the enqueue-ordered list — the spec the index
    must match: generation/event eligibility, node locality (node-less
    waiters always eligible), strict priority, chip-coverage preference
    WITHIN the winning priority, then smallest fair share, then
    earliest enqueue."""
    usage = usage or {}
    quotas = quotas or {}
    cands = [w for w in ordered
             if w.tried_gen < gen and not w.event.is_set()]
    if node is not None:
        cands = [w for w in cands if not w.node or w.node == node]
    if not cands:
        return None
    top = max(_rank(w.priority) for w in cands)
    cands = [w for w in cands if _rank(w.priority) == top]
    if chips > 0:
        covered = [w for w in cands if w.chips <= chips]
        if covered:
            cands = covered

    def share(w):
        return usage.get(w.tenant, 0) / (quotas.get(w.tenant) or 1e9)

    return min(cands, key=lambda w: (share(w), ordered.index(w)))


TENANTS = ("teamA", "teamB", "teamC", "hog")
NODES = ("node-a", "node-b", "node-c")


def test_randomized_equivalence_1k_interleavings():
    """The acceptance pin: across 1k randomized park / wake / timeout /
    preempt interleavings, the index and the brute-force list scan pick
    the SAME waiter for every signal — including node/chips-hinted
    signals, and (hint-less) the legacy linear path too."""
    rng = random.Random(0xA11CE)
    indexed = WaiterQueue(indexed=True)
    linear = WaiterQueue(indexed=False)
    ordered: list[W] = []
    usage = {t: 0 for t in TENANTS}
    quotas = {"teamA": 8, "teamB": 4, "teamC": 2}   # hog unlimited
    gen = 0
    selects = 0
    for step in range(1000):
        op = rng.random()
        if op < 0.45 or not ordered:
            # park (sometimes a node-less gang)
            w = W(tenant=rng.choice(TENANTS),
                  priority=rng.choice(consts.PRIORITIES),
                  chips=rng.randint(1, 8),
                  node=rng.choice(NODES),
                  gang=rng.random() < 0.1)
            w.tried_gen = gen        # parks at the current generation
            ordered.append(w)
            indexed.add(w)
            linear.add(w)
        elif op < 0.60:
            # timeout / grant / preempted departure
            w = rng.choice(ordered)
            ordered.remove(w)
            indexed.remove(w)
            linear.remove(w)
        elif op < 0.70:
            # a woken waiter retried and failed: consumes its wake
            woken = [w for w in ordered if w.event.is_set()]
            if woken:
                rng.choice(woken).event.clear()
        elif op < 0.80:
            # lease churn moves the fair-share landscape
            usage[rng.choice(TENANTS)] = rng.randint(0, 10)
        else:
            # capacity signal, randomly hinted
            gen += 1
            node = rng.choice((None,) + NODES)
            chips = rng.choice((0, 0, 1, 2, 4, 8))
            expect = reference_select(ordered, gen, node=node,
                                      chips=chips, usage=usage,
                                      quotas=quotas)
            got, _ = indexed.select(gen, node=node, chips=chips,
                                    usage_fn=lambda: dict(usage),
                                    quota_fn=quotas.get)
            assert got is expect, \
                (f"step {step}: index chose {got}, reference chose "
                 f"{expect} (gen={gen} node={node} chips={chips})")
            if node is None and chips == 0:
                lin, _ = linear.select(gen,
                                       usage_fn=lambda: dict(usage),
                                       quota_fn=quotas.get)
                assert lin is expect, \
                    f"step {step}: linear path diverged: {lin}"
            if got is not None:
                got.tried_gen = gen
                got.event.set()
            selects += 1
    assert selects > 100                     # the drive actually drove


def test_evaluations_scale_with_node_candidates_not_total():
    """The perf pin: 1000 waiters parked on node-b must not be examined
    by a node-a signal — the index touches node-a's candidates (plus
    node-less gangs), the linear scan pays the whole queue."""
    indexed = WaiterQueue(indexed=True)
    linear = WaiterQueue(indexed=False)
    for i in range(1000):
        w = W(tenant=TENANTS[i % 3], node="node-b", chips=1 + i % 4)
        indexed.add(w)
        linear.add(w)
    locals_ = [W(tenant=TENANTS[i % 2], node="node-a") for i in range(5)]
    gang = W(tenant="teamC", gang=True)
    for w in (*locals_, gang):
        indexed.add(w)
        linear.add(w)
    chosen, evaluated = indexed.select(1, node="node-a", chips=1)
    assert chosen in (*locals_, gang)
    # bucket fronts only: a handful of examinations, not the 1006-scan
    assert evaluated <= 3 * len(TENANTS) * len(consts.PRIORITIES), \
        f"indexed signal examined {evaluated} waiters"
    _, linear_cost = linear.select(1)
    assert linear_cost == 1006      # what the rescan used to pay


def test_membership_surface_matches_the_list_it_replaced():
    q = WaiterQueue()
    a, b = W(priority="high"), W(priority="low", gang=True)
    q.add(a)
    q.add(b)
    assert list(q) == [a, b] and len(q) == 2 and a in q
    assert q == [a, b] and not (q == [b, a])
    assert q.count("high") == 1 and q.count("low") == 1
    assert q.gang_count() == 1
    assert q.oldest_enqueued_at() == a.enqueued_at
    q.remove(a)
    q.remove(a)                     # tolerant, like the guarded remove
    assert q == [b] and q.count("high") == 0
    q.remove(b)
    assert q == [] and q.oldest_enqueued_at() is None \
        and q.gang_count() == 0


def test_generation_and_event_filters_hold():
    """A waiter that was already woken this generation (tried_gen) or
    holds an unconsumed wake (event set) is not a candidate — the baton
    discipline the broker's wakeup chain is built on."""
    q = WaiterQueue()
    first, second = W(tenant="teamA"), W(tenant="teamB")
    q.add(first)
    q.add(second)
    got, _ = q.select(1)
    assert got is first             # equal shares -> earliest enqueue
    got.tried_gen = 1
    got.event.set()
    got, _ = q.select(1)
    assert got is second            # first is no longer eligible
    second.tried_gen = 1
    second.event.set()
    got, _ = q.select(1)
    assert got is None
    first.event.clear()
    second.event.clear()
    got, _ = q.select(2)            # new generation re-arms both
    assert got is first


def test_chip_coverage_preference_never_inverts_priority():
    """2 freed chips prefer a 2-chip candidate over an 8-chip one —
    but only WITHIN a priority: a high 8-chip waiter still beats a
    normal 2-chip waiter (it may preempt its way to the rest)."""
    q = WaiterQueue()
    big_high = W(priority="high", chips=8, node="node-a")
    small_normal = W(priority="normal", chips=2, node="node-a")
    q.add(big_high)
    q.add(small_normal)
    got, _ = q.select(1, node="node-a", chips=2)
    assert got is big_high
    q2 = WaiterQueue()
    big = W(priority="normal", chips=8, node="node-a")
    small = W(priority="normal", chips=2, node="node-a")
    q2.add(big)
    q2.add(small)
    got, _ = q2.select(1, node="node-a", chips=2)
    assert got is small             # coverage preference within the tier
    got, _ = q2.select(1, node="node-a", chips=1)
    assert got is big               # nothing covered: earliest enqueue


def test_waiter_index_knob_plumbs_from_env():
    from gpumounter_tpu.master.admission import BrokerConfig
    from gpumounter_tpu.utils.config import Settings
    assert Settings().waiter_index is True
    assert Settings.from_env({}).waiter_index is True
    assert Settings.from_env({"TPU_WAITER_INDEX": "0"}).waiter_index \
        is False
    assert BrokerConfig().waiter_index is True
    off = BrokerConfig.from_settings(
        Settings.from_env({"TPU_WAITER_INDEX": "0"}))
    assert off.waiter_index is False
    from gpumounter_tpu.master.admission import AttachBroker
    from gpumounter_tpu.k8s.client import FakeKubeClient
    broker = AttachBroker(FakeKubeClient(), off)
    assert broker._waiters.indexed is False
