"""Worker orchestration tests: the AddTPU/RemoveTPU flows of
``pkg/server/gpu-mount/server.go`` over the WorkerRig (real allocator, real
cgroup v1 controller on a fixture tree, recording mknod layer)."""

import os

import pytest

from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.errors import ActuationError, MountPolicyError

from tests.helpers import WorkerRig


@pytest.fixture
def rig(fake_host):
    return WorkerRig(fake_host)


def test_add_single_mount_success(rig):
    out = rig.service.add_tpu("workload", "default", 2, False)
    assert out.result is consts.AddResult.SUCCESS
    assert len(out.chips) == 2
    # two one-chip slave pods
    assert len(rig.sim.slave_pods()) == 2
    # cgroup allow written + device nodes created through the live pid
    assert os.path.exists(os.path.join(rig.cgroup_dir, "devices.allow"))
    assert [c[1] for c in rig.actuator.created] == ["/dev/accel0",
                                                    "/dev/accel1"]


def test_add_entire_mount_one_slave_pod(rig):
    out = rig.service.add_tpu("workload", "default", 4, True)
    assert out.result is consts.AddResult.SUCCESS
    assert len(out.chips) == 4
    assert len(rig.sim.slave_pods()) == 1


def test_add_pod_not_found(rig):
    out = rig.service.add_tpu("ghost", "default", 1, False)
    assert out.result is consts.AddResult.POD_NOT_FOUND


def test_add_pod_not_running(rig):
    rig.sim.kube.set_pod_status("default", "workload", phase="Pending")
    out = rig.service.add_tpu("workload", "default", 1, False)
    assert out.result is consts.AddResult.POD_NOT_FOUND
    assert "Pending" in out.message


def test_add_insufficient(rig):
    out = rig.service.add_tpu("workload", "default", 99, False)
    assert out.result is consts.AddResult.INSUFFICIENT_TPU
    assert rig.sim.slave_pods() == []          # cleanup happened


def test_add_policy_rejections(rig):
    assert rig.service.add_tpu("workload", "default", 4, True).result is \
        consts.AddResult.SUCCESS
    # entire-mounted pod refuses anything further (ref util.go:207-226)
    with pytest.raises(MountPolicyError):
        rig.service.add_tpu("workload", "default", 1, False)
    with pytest.raises(MountPolicyError):
        rig.service.add_tpu("workload", "default", 1, True)


def test_add_single_then_single_composes(rig):
    assert rig.service.add_tpu("workload", "default", 1, False).result is \
        consts.AddResult.SUCCESS
    assert rig.service.add_tpu("workload", "default", 1, False).result is \
        consts.AddResult.SUCCESS
    assert len(rig.sim.slave_pods()) == 2
    # but an entire-mount on top is denied
    with pytest.raises(MountPolicyError):
        rig.service.add_tpu("workload", "default", 2, True)


def test_add_zero_chips_rejected(rig):
    with pytest.raises(MountPolicyError):
        rig.service.add_tpu("workload", "default", 0, False)


def test_add_rollback_on_actuation_failure(rig):
    rig.actuator.fail_on_create = True
    with pytest.raises(ActuationError):
        rig.service.add_tpu("workload", "default", 2, False)
    # slave pods rolled back (ref server.go:87-92), chips free again
    assert rig.sim.slave_pods() == []
    assert rig.sim.podresources.assignments == {}
    rig.actuator.fail_on_create = False
    out = rig.service.add_tpu("workload", "default", 4, True)
    assert out.result is consts.AddResult.SUCCESS


def test_remove_full_roundtrip(rig):
    added = rig.service.add_tpu("workload", "default", 2, False)
    uuids = [c.uuid for c in added.chips]
    out = rig.service.remove_tpu("workload", "default", uuids, False)
    assert out.result is consts.RemoveResult.SUCCESS
    assert rig.sim.slave_pods() == []
    assert [r[1] for r in rig.actuator.removed] == ["/dev/accel0",
                                                    "/dev/accel1"]
    # devices.deny written for both chips
    assert os.path.exists(os.path.join(rig.cgroup_dir, "devices.deny"))
    # pod is mountable again
    assert rig.service.add_tpu("workload", "default", 1, True).result is \
        consts.AddResult.SUCCESS


def test_remove_empty_uuids_removes_all(rig):
    rig.service.add_tpu("workload", "default", 2, False)
    out = rig.service.remove_tpu("workload", "default", [], False)
    assert out.result is consts.RemoveResult.SUCCESS
    assert rig.sim.slave_pods() == []


def test_remove_pod_not_found(rig):
    out = rig.service.remove_tpu("ghost", "default", [], False)
    assert out.result is consts.RemoveResult.POD_NOT_FOUND


def test_remove_nothing_mounted(rig):
    out = rig.service.remove_tpu("workload", "default", [], False)
    assert out.result is consts.RemoveResult.TPU_NOT_FOUND


def test_remove_unknown_uuid(rig):
    rig.service.add_tpu("workload", "default", 1, False)
    out = rig.service.remove_tpu("workload", "default", ["bogus"], False)
    assert out.result is consts.RemoveResult.TPU_NOT_FOUND


def test_remove_busy_reports_pids(rig):
    added = rig.service.add_tpu("workload", "default", 1, False)
    chip = added.chips[0]
    rig.sim.enumerator.busy_pids = {chip.device_path: [rig.pid]}
    out = rig.service.remove_tpu("workload", "default", [chip.uuid], False)
    assert out.result is consts.RemoveResult.TPU_BUSY
    assert out.busy_pids == [rig.pid]
    assert rig.sim.slave_pods() != []          # nothing deleted


def test_remove_busy_force_kills(rig):
    added = rig.service.add_tpu("workload", "default", 1, False)
    chip = added.chips[0]
    rig.sim.enumerator.busy_pids = {chip.device_path: [rig.pid]}
    out = rig.service.remove_tpu("workload", "default", [chip.uuid], True)
    assert out.result is consts.RemoveResult.SUCCESS
    assert rig.actuator.killed == [(rig.pid, 9)]
    assert rig.sim.slave_pods() == []


def test_remove_partial_entire_mount_refused(rig):
    added = rig.service.add_tpu("workload", "default", 4, True)
    one = added.chips[0].uuid
    out = rig.service.remove_tpu("workload", "default", [one], False)
    assert out.result is consts.RemoveResult.TPU_NOT_FOUND
    assert "partial" in out.message
    # whole set works
    out = rig.service.remove_tpu(
        "workload", "default", [c.uuid for c in added.chips], False)
    assert out.result is consts.RemoveResult.SUCCESS


def test_metrics_recorded(rig):
    from gpumounter_tpu.utils.metrics import REGISTRY
    before = REGISTRY.attach_latency.count
    rig.service.add_tpu("workload", "default", 1, False)
    assert REGISTRY.attach_latency.count == before + 1
    assert REGISTRY.attach_results.value(result="SUCCESS") >= 1


def test_attach_detach_cost_one_kubelet_list_each(rig):
    """Round-2 VERDICT weak #4 / next-round #5: a 4-chip entire-mount must
    take O(1) kubelet PodResources LISTs (one snapshot threaded through),
    not ~N+3. Same bound for detach and status."""
    rig.sim.podresources.list_calls = 0
    out = rig.service.add_tpu("workload", "default", 4,
                              is_entire_mount=True)
    assert out.result is consts.AddResult.SUCCESS
    assert rig.sim.podresources.list_calls <= 2

    rig.sim.podresources.list_calls = 0
    rig.service.tpu_status("workload", "default")
    assert rig.sim.podresources.list_calls <= 2

    rig.sim.podresources.list_calls = 0
    out = rig.service.remove_tpu("workload", "default", [], force=False)
    assert out.result is consts.RemoveResult.SUCCESS
    assert rig.sim.podresources.list_calls <= 2


def test_lag_retry_lists_once_per_round_not_per_pod(fake_host):
    """With 4 one-chip slave pods and a lagging kubelet, each retry round
    costs ONE LIST covering all pods (round-2 did one per pod per round)."""
    from tests.helpers import WorkerRig
    rig = WorkerRig(fake_host, n_chips=4, kubelet_lag_s=0.5)
    rig.sim.podresources.list_calls = 0
    out = rig.service.add_tpu("workload", "default", 4,
                              is_entire_mount=False)
    assert out.result is consts.AddResult.SUCCESS
    # rounds needed ≈ lag/backoff schedule (0.2+0.4+... covers 0.5s in ≤4
    # rounds); allow slack but far below the old per-pod cost (4 pods × 4
    # rounds = 16+)
    assert rig.sim.podresources.list_calls <= 6


def test_events_audit_trail(rig):
    """Attach/detach/busy outcomes post core/v1 Events on the target pod
    (kubectl-describe visibility). Best-effort: a failing events API must
    not fail the RPC."""
    import time as time_mod

    def wait_events(n, timeout=5.0):
        deadline = time_mod.monotonic() + timeout
        while time_mod.monotonic() < deadline:
            if len(rig.sim.kube.events) >= n:
                return
            time_mod.sleep(0.01)
        raise AssertionError(
            f"only {len(rig.sim.kube.events)} events after {timeout}s")

    out = rig.service.add_tpu("workload", "default", 2,
                              is_entire_mount=True)
    assert out.result is consts.AddResult.SUCCESS
    wait_events(1)
    events = rig.sim.kube.events
    assert [e["reason"] for e in events] == ["TPUAttached"]
    ev = events[0]
    assert ev["type"] == "Normal"
    assert ev["involvedObject"]["name"] == "workload"
    assert ev["source"]["component"] == "tpu-mounter-worker"
    assert "2 TPU chip(s)" in ev["message"]

    out = rig.service.remove_tpu("workload", "default", [], force=False)
    assert out.result is consts.RemoveResult.SUCCESS
    wait_events(2)
    assert [e["reason"] for e in events] == ["TPUAttached", "TPUDetached"]

    # insufficient → Warning event
    out = rig.service.add_tpu("workload", "default", 99,
                              is_entire_mount=False)
    assert out.result is consts.AddResult.INSUFFICIENT_TPU
    wait_events(3)
    assert events[-1]["reason"] == "TPUAttachFailed"
    assert events[-1]["type"] == "Warning"

    # identical WARNING (pod, reason) within the suppression window is not
    # re-posted; success events are never suppressed
    out = rig.service.add_tpu("workload", "default", 99,
                              is_entire_mount=False)
    assert out.result is consts.AddResult.INSUFFICIENT_TPU
    time_mod.sleep(0.2)
    assert len(events) == 3

    # events API failure is swallowed (success events bypass suppression,
    # so this genuinely exercises the broken client)
    calls = []

    def broken(ns, ev):
        calls.append(ev["reason"])
        raise RuntimeError("rbac denied")
    rig.sim.kube.create_event = broken
    out = rig.service.add_tpu("workload", "default", 1,
                              is_entire_mount=False)
    assert out.result is consts.AddResult.SUCCESS
    deadline = time_mod.monotonic() + 5
    while time_mod.monotonic() < deadline and not calls:
        time_mod.sleep(0.01)
    assert calls == ["TPUAttached"]      # the POST ran and raised
    assert len(events) == 3              # nothing recorded
