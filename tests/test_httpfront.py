"""Multiplexed gateway front (master/httpfront.py): HTTP/1.1 keep-alive,
selector-owned idle connections, bounded workers, pipelining, and
connection admission BEFORE thread allocation."""

import http.client
import json
import socket
import threading
import time

import pytest

from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.master.discovery import WorkerDirectory
from gpumounter_tpu.master.gateway import MasterGateway
from gpumounter_tpu.master.httpfront import MultiplexedHTTPServer
from gpumounter_tpu.utils.metrics import REGISTRY


@pytest.fixture
def gateway():
    kube = FakeKubeClient()
    return MasterGateway(kube, WorkerDirectory(kube))


def _serve(gateway, **kwargs):
    server = gateway.serve(port=0, address="127.0.0.1", **kwargs)
    return server


def test_default_front_is_multiplexed(gateway):
    server = _serve(gateway)
    try:
        assert isinstance(server, MultiplexedHTTPServer)
    finally:
        server.shutdown()


def test_threaded_front_still_available(gateway):
    from http.server import ThreadingHTTPServer
    server = _serve(gateway, front="threaded")
    try:
        assert isinstance(server, ThreadingHTTPServer)
    finally:
        server.shutdown()


def test_keep_alive_serves_many_requests_on_one_connection(gateway):
    server = _serve(gateway)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.server_port,
                                          timeout=10)
        for _ in range(20):
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            assert resp.version == 11           # HTTP/1.1
            assert json.loads(resp.read())["status"] == "ok"
        conn.close()
    finally:
        server.shutdown()


def test_routes_and_errors_unchanged_through_the_front(gateway):
    """The front is transport only: routing, 404s, 405+Allow, and
    Retry-After behave exactly as through the threaded server."""
    server = _serve(gateway)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.server_port,
                                          timeout=10)
        conn.request("GET", "/no/such/route")
        resp = conn.getresponse()
        assert resp.status == 404
        assert json.loads(resp.read())["result"] == "NoSuchRoute"
        conn.request("POST", "/healthz")
        resp = conn.getresponse()
        assert resp.status == 405
        assert resp.headers["Allow"] == "GET"
        resp.read()
        conn.request("GET", "/version")
        resp = conn.getresponse()
        assert resp.status == 200
        assert "version" in json.loads(resp.read())
        conn.close()
    finally:
        server.shutdown()


def test_pipelined_requests_all_answered_in_order(gateway):
    server = _serve(gateway)
    try:
        sock = socket.create_connection(("127.0.0.1", server.server_port),
                                        timeout=10)
        request = (b"GET /healthz HTTP/1.1\r\n"
                   b"Host: x\r\n\r\n")
        sock.sendall(request * 3)
        sock.settimeout(2.0)
        data = b""
        deadline = time.monotonic() + 10
        while data.count(b"HTTP/1.1 200") < 3 \
                and time.monotonic() < deadline:
            try:
                chunk = sock.recv(65536)
            except socket.timeout:
                break
            if not chunk:
                break
            data += chunk
        assert data.count(b"HTTP/1.1 200") == 3, data
        sock.close()
    finally:
        server.shutdown()


def test_concurrent_connections_multiplex_over_bounded_workers(gateway):
    server = _serve(gateway, workers=4)
    results = []
    try:
        def one():
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.server_port, timeout=15)
            for _ in range(5):
                conn.request("GET", "/healthz")
                results.append(
                    json.loads(conn.getresponse().read())["status"])
            conn.close()
        threads = [threading.Thread(target=one) for _ in range(32)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30)
        assert results.count("ok") == 32 * 5
        assert server.workers == 4          # bounded, not per-request
    finally:
        server.shutdown()


def test_admission_rejects_beyond_connection_bound(gateway):
    """Past max_conns, a NEW connection is answered 503 straight from
    the acceptor — no handler, no worker thread — and counted."""
    server = _serve(gateway, max_conns=2)
    held = []
    try:
        for _ in range(2):
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.server_port, timeout=10)
            conn.connect()
            held.append(conn)
        rejected_before = REGISTRY.gateway_rejected.value()
        deadline = time.monotonic() + 10
        status = None
        while time.monotonic() < deadline and status != 503:
            # the two held conns register asynchronously; retry until the
            # acceptor sees the bound as saturated
            probe = http.client.HTTPConnection(
                "127.0.0.1", server.server_port, timeout=5)
            try:
                probe.request("GET", "/healthz")
                status = probe.getresponse().status
            except (http.client.HTTPException, OSError):
                status = None
            finally:
                probe.close()
            if status != 503:
                time.sleep(0.05)
        assert status == 503
        assert REGISTRY.gateway_rejected.value() > rejected_before
    finally:
        for conn in held:
            conn.close()
        server.shutdown()


def test_inflight_gauge_and_peak_track_admitted_requests(gateway):
    server = _serve(gateway, workers=8)
    try:
        def one():
            conn = http.client.HTTPConnection(
                "127.0.0.1", server.server_port, timeout=15)
            conn.request("GET", "/healthz")
            conn.getresponse().read()
            conn.close()
        threads = [threading.Thread(target=one) for _ in range(16)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=15)
        assert server.peak_inflight >= 1
        # disconnect EOFs drain asynchronously; the gauge must settle at 0
        deadline = time.monotonic() + 5
        while REGISTRY.gateway_inflight.value() != 0 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert REGISTRY.gateway_inflight.value() == 0
    finally:
        server.shutdown()


def test_client_disconnect_while_idle_is_reaped(gateway):
    server = _serve(gateway)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.server_port,
                                          timeout=10)
        conn.request("GET", "/healthz")
        conn.getresponse().read()
        conn.close()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with server._conns_lock:
                if not server._conns:
                    break
            time.sleep(0.02)
        with server._conns_lock:
            assert not server._conns
    finally:
        server.shutdown()
