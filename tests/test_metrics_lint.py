"""Metric naming lint: every registered family must follow the repo's
Prometheus conventions, so a new metric can't silently break the shipped
dashboards/alerts (which select on the ``tpumounter_`` prefix and the
unit suffixes)."""

import re

from gpumounter_tpu.utils import metrics


NAME_RE = re.compile(r"^tpumounter_[a-z0-9_]+$")

# Gauges describe a current level, named for the noun they measure (or the
# standard _info pattern); cumulative/unit suffixes on a gauge would lie
# about its semantics to every PromQL consumer.
GAUGE_FORBIDDEN_SUFFIXES = ("_total", "_seconds", "_count", "_sum")


def test_every_family_matches_naming_convention():
    reg = metrics.Registry()
    families = reg.families()
    assert len(families) >= 12          # the registry is non-trivial
    for fam in families:
        assert NAME_RE.match(fam.name), \
            f"{fam.name}: not tpumounter_[a-z0-9_]+"
        if isinstance(fam, metrics.Counter):
            assert fam.name.endswith("_total"), \
                f"counter {fam.name} must end in _total"
        elif isinstance(fam, (metrics.Histogram, metrics.LabeledHistogram)):
            assert fam.name.endswith("_seconds"), \
                f"histogram {fam.name} must end in _seconds (this repo " \
                "only measures durations)"
        elif isinstance(fam, metrics.Gauge):
            assert not fam.name.endswith(GAUGE_FORBIDDEN_SUFFIXES), \
                f"gauge {fam.name} carries a counter/unit suffix"
        else:
            raise AssertionError(f"unknown family type {type(fam)}")


def test_every_family_has_help_and_renders_headers():
    reg = metrics.Registry()
    for fam in reg.families():
        # uniform attribute across Counter/Histogram/Gauge (the Gauge used
        # to store help_text, breaking generic consumers)
        assert isinstance(fam.help, str) and fam.help, fam.name
        rendered = list(fam.render())
        assert rendered[0] == f"# HELP {fam.name} {fam.help}"
        assert rendered[1].startswith(f"# TYPE {fam.name} ")


def test_build_info_identifies_the_binary():
    import gpumounter_tpu
    reg = metrics.Registry()
    assert reg.build_info.value(
        version=gpumounter_tpu.__version__) == 1.0
    text = reg.render_text()
    assert (f'tpumounter_build_info{{version='
            f'"{gpumounter_tpu.__version__}"}} 1') in text
