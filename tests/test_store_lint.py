"""Intent-store lint (pattern of test_admission_lint / test_events_lint):
broker state is cluster ground truth ONLY if every mutation writes
through the store layer (master/store.py). Structurally: every
LeaseTable method that mutates the lease dict must reference a store
seam, every waiter park/resolve site must persist/unpersist its intent
record, and no module outside the store/election pair may touch the
ConfigMap CAS primitives. A new mutation path added without store wiring
fails here instead of shipping state a failed-over peer cannot see.
"""

import ast

from gpumounter_tpu.master import (admission, election, fleet, gateway,
                                   lease, slicetxn, store)

from tests.test_retry_lint import (_functions, _names_used,
                                   _referencing_functions)

# LeaseTable methods that mutate self._leases WITHOUT a store write, by
# design — each exemption is the point of the method, not an oversight:
#   evict_where   — shard hand-off: the records now belong to the new
#                   leader; deleting them would destroy the state it is
#                   about to rehydrate
#   merge_records — rehydration INTO memory FROM the store; writing back
#                   would be a no-op echo
SANCTIONED_MEMORY_ONLY = {"LeaseTable.evict_where",
                          "LeaseTable.merge_records"}

STORE_SEAMS = {"_store_put", "_store_del", "_store_sync"}


def _mutates_leases(funcdef) -> bool:
    """True when the function writes the lease dict: subscript
    assignment/deletion, .pop()/.clear()/.update(), or rebinding
    self._leases wholesale."""
    for node in ast.walk(funcdef):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for target in targets:
                if isinstance(target, ast.Subscript) and \
                        isinstance(target.value, ast.Attribute) and \
                        target.value.attr == "_leases":
                    return True
                if isinstance(target, ast.Attribute) and \
                        target.attr == "_leases":
                    return True
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Subscript) and \
                        isinstance(target.value, ast.Attribute) and \
                        target.value.attr == "_leases":
                    return True
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("pop", "clear", "update", "setdefault"):
            inner = node.func.value
            if isinstance(inner, ast.Attribute) and \
                    inner.attr == "_leases":
                return True
    return False


def test_every_lease_mutation_writes_through_the_store():
    """No LeaseTable mutation site escapes the store layer: any method
    that touches the lease dict either references a store seam or is on
    the sanctioned memory-only list (with its reason documented above)."""
    for qual, funcdef in _functions(lease).items():
        if not qual.startswith("LeaseTable.") or "." in \
                qual[len("LeaseTable."):]:
            continue
        if not _mutates_leases(funcdef):
            continue
        if qual in SANCTIONED_MEMORY_ONLY:
            continue
        names = _names_used(funcdef)
        assert names & STORE_SEAMS, \
            f"{qual} mutates the lease table without a store write — " \
            "a failed-over peer would rehydrate stale state"


def test_sanctioned_exemptions_still_exist():
    """The exemption list must not rot: every sanctioned name is a real
    mutating method (a rename would silently re-arm the lint on the old
    name and skip the new one)."""
    funcs = _functions(lease)
    for qual in SANCTIONED_MEMORY_ONLY:
        assert qual in funcs, f"{qual} no longer exists"
        assert _mutates_leases(funcs[qual]), f"{qual} no longer mutates"
        # and they must NOT write the store — if one starts writing,
        # remove it from the list so the lint covers it
        assert not (_names_used(funcs[qual]) & STORE_SEAMS), qual


def test_store_seams_are_the_only_record_writers_in_lease():
    """LeaseRecord construction (the serialize half of the round-trip)
    is confined to the store seams — no method hand-rolls a record."""
    hits = _referencing_functions(lease, "LeaseRecord")
    assert hits <= {"LeaseTable._store_put", "LeaseTable._store_sync",
                    "LeaseTable.flush_renewals"}, hits


def test_waiter_park_and_resolve_sites_persist_intent():
    """The queue path persists on park and unpersists on EVERY exit
    (grant, timeout, error, hand-off — the finally block), and the
    adoption drain resolves its record no matter how the re-run ends."""
    funcs = _functions(admission)
    queued = _names_used(funcs["AttachBroker._attach_queued"])
    assert "_persist_waiter" in queued, \
        "_attach_queued parks a waiter without persisting its intent"
    assert "_unpersist_waiter" in queued, \
        "_attach_queued resolves a waiter without removing its record"
    adopted = _names_used(funcs["AttachBroker._run_adopted"])
    assert "_unpersist_rid" in adopted, \
        "_run_adopted can leave a resolved intent record behind"
    # parking happens in exactly two places: the single-attach queue
    # path (persisted as a waiter record above) and the gang path, whose
    # durable intent is the slice TXN record — pinned below. (The queue
    # became a WaiterQueue in the 10k-admission PR; ``_waiters.add`` is
    # the one enqueue verb.)
    appenders = {
        qual.split(".", 1)[0] + "." + qual.split(".")[1]
        for qual, funcdef in funcs.items()
        if qual.startswith("AttachBroker.")
        and any(isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "add"
                and isinstance(n.func.value, ast.Attribute)
                and n.func.value.attr == "_waiters"
                for n in ast.walk(funcdef))}
    assert appenders == {"AttachBroker._attach_queued",
                         "AttachBroker.park_gang"}, appenders


def test_slice_txn_intent_is_persisted_around_the_fanout():
    """The crash-safe slice protocol: attach() writes the intent record
    BEFORE the fan-out (and the per-host marker callback persists as
    hosts land), every terminal path resolves it (commit deletes, a
    clean abort deletes, an unclean abort re-persists for re-adoption),
    and the gang's park site is attach's own loop — no slice waits
    without a durable record."""
    funcs = _functions(slicetxn)
    attach = _names_used(funcs["SliceTxnManager.attach"])
    assert "_persist_txn" in attach, \
        "SliceTxnManager.attach fans out without writing its intent"
    commit = _names_used(funcs["SliceTxnManager._commit"])
    assert "_unpersist_txn" in commit
    abort = _names_used(funcs["SliceTxnManager._abort"])
    assert {"_unpersist_txn", "_persist_txn"} <= abort
    marker = _names_used(funcs["SliceTxnManager._marker_callback"])
    assert "_persist_txn" in marker, \
        "per-host commit markers are not persisted as hosts land"
    run = _names_used(funcs["SliceTxnManager._run"])
    assert "park_gang" in run and "unpark_gang" in run, \
        "the gang park/unpark pair moved out of the txn-scoped loop"


def test_configmap_cas_is_confined_to_store_and_election():
    """Only the store (state records) and the election (lock records)
    may write ConfigMaps; a broker/gateway/fleet mutation that bypasses
    them would dodge both the fence check and the CAS discipline."""
    for module in (admission, lease, gateway, fleet, slicetxn):
        for qual, funcdef in _functions(module).items():
            names = _names_used(funcdef)
            bad = names & {"patch_config_map", "create_config_map",
                           "delete_config_map"}
            assert not bad, \
                f"{module.__name__}.{qual} writes ConfigMaps directly " \
                f"({bad}) — all broker state goes through the store"


def test_store_cas_is_one_seam_with_the_fence_check_inside():
    """Every store write funnels through _cas, where the fence token
    check and the annotation patch are ONE atomic step — the split-brain
    impossibility argument (docs/guide/HA.md) depends on no second
    write path existing."""
    assert _referencing_functions(store, "patch_config_map") == \
        {"IntentStore._cas"}
    assert _referencing_functions(store, "create_config_map") == \
        {"IntentStore._cas"}
    cas = _functions(store)["IntentStore._cas"]
    names = _names_used(cas)
    assert "StoreFencedError" in names, \
        "_cas no longer enforces the fencing token"
    # and the public write path reaches it
    assert "_cas" in _names_used(_functions(store)["IntentStore._write"])


def test_record_mutations_route_through_the_coalescer_seam():
    """The 10k-admission group-commit contract: NO request-thread code
    path issues a per-record CAS. Every record mutation crosses
    ``IntentStore._mutate`` (the coalescer seam); ``_write`` — the
    per-record CAS — is reachable only from that seam (the sanctioned
    TPU_STORE_GROUP_COMMIT=0 off-path) and the dirty replay; and
    ``_cas`` itself has exactly four sanctioned-with-reason callers:
      _write        — the per-record off-path + dirty replay
      put_leases    — already one CAS per shard by construction
      poke_peers    — the fence-exempt capacity stamp (no record state)
      flush_pending — the group-commit flush (ONE fused CAS per shard)
    A new direct caller is a new serialization point on the per-shard
    CAS stream and fails here instead of shipping."""
    funcs = _functions(store)
    for qual in ("IntentStore.put_lease", "IntentStore.delete_lease",
                 "IntentStore.put_waiter", "IntentStore.delete_waiter",
                 "IntentStore.put_slice_txn",
                 "IntentStore.delete_slice_txn"):
        names = _names_used(funcs[qual])
        assert "_mutate" in names, \
            f"{qual} mutates a record without the coalescer seam"
        assert not ({"_cas", "_write"} & names), \
            f"{qual} bypasses the coalescer seam with a direct CAS"
    # _put_leases_locked's _write is its DEGRADATION path only: a
    # failed batch falls back to per-record writes so each record gets
    # its own dirty-parking — not a hot-path caller. (put_leases runs
    # its CAS under _flush_mutex so an in-flight coalescer flush can
    # never land a stale batch over the fresh sync.)
    assert _referencing_functions(store, "_write") == \
        {"IntentStore._mutate", "IntentStore.flush_dirty",
         "IntentStore._put_leases_locked"}
    assert _referencing_functions(store, "_cas") == {
        "IntentStore._write", "IntentStore._put_leases_locked",
        "IntentStore.poke_peers", "IntentStore.flush_pending"}
    assert "_flush_mutex" in _names_used(
        _functions(store)["IntentStore.put_leases"]), \
        "put_leases lost its serialization against the coalescer flush"


def test_group_commit_flush_keeps_the_durability_rules():
    """The fused flush must keep the per-record disciplines: park on
    no-live-token AND on apiserver failure (the dirty queue), surface a
    real fence through on_fenced (demotion) — and the broker tick
    drives flush_pending as the backstop before the dirty replay."""
    flush = _functions(store)["IntentStore.flush_pending"]
    names = _names_used(flush)
    assert "_park" in names, \
        "a refused fused batch must park dirty, not vanish"
    assert "on_fenced" in names and "StoreFencedError" in names, \
        "flush_pending no longer surfaces fences for demotion"
    tick = _functions(admission)["AttachBroker.tick"]
    assert "flush_pending" in _names_used(tick), \
        "the broker tick lost the group-commit flush backstop"


def test_election_lock_writes_carry_the_full_annotation_set():
    """Lock mutations (create/renew/takeover) all build their
    annotations through _lock_annotations — holder, url, fence and
    deadline move together, so an observer can never read a lock with a
    new fence but a stale holder."""
    hits = _referencing_functions(election, "_lock_annotations")
    assert hits == {"ShardElection._try_create", "ShardElection._renew",
                    "ShardElection._takeover"}, hits
