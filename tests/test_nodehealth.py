"""Node failure domain, unit half: the health state machine
(master/nodehealth.py), the broker's lease-fencing seam, the reaper's
fence-after-N-failures satellite, the worker-directory negative cache,
and the byte-for-byte pins for TPU_NODE_HEALTH=0 / the subsystem idle.
The chaos acceptance (kill a live worker / repair a live slice) lives
in tests/test_node_chaos.py."""

import time

from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.master import nodehealth
from gpumounter_tpu.master.admission import AttachBroker, BrokerConfig
from gpumounter_tpu.master.discovery import (WorkerDirectory,
                                             WorkerNotFoundError)
from gpumounter_tpu.master.nodehealth import NodeHealthTracker
from gpumounter_tpu.testing.sim import make_tpu_node, worker_pod
from gpumounter_tpu.utils import consts
from gpumounter_tpu.utils.events import EVENTS
from gpumounter_tpu.utils.metrics import REGISTRY

import pytest


def _feed(fresh=True, missed=0, healthz="ok"):
    return {"fresh": fresh, "missed_ticks": missed, "healthz": healthz}


# -- the state machine ---------------------------------------------------------

def test_states_escalate_suspect_then_dead_with_events():
    dead, drained = [], []
    tracker = NodeHealthTracker(on_dead=dead.append,
                                on_drain=drained.append,
                                suspect_after_ticks=2,
                                dead_after_ticks=4)
    node = "nh-esc-node"
    tracker.ingest({node: _feed(fresh=True)})
    assert tracker.state(node) == "healthy"
    assert not tracker.cordoned(node)
    for missed in (1, 2, 3, 4):
        tracker.ingest({node: _feed(fresh=False, missed=missed)})
    assert tracker.state(node) == "dead"
    assert tracker.cordoned(node)
    assert dead == [node]
    assert drained == []
    kinds = [e["kind"] for e in EVENTS.tail(200)
             if e.get("node") == node]
    assert kinds == ["node_suspect", "node_dead"]
    assert REGISTRY.node_health_state.value(node=node) == 3.0
    # dying again without recovering must not re-fire on_dead
    tracker.ingest({node: _feed(fresh=False, missed=9)})
    assert dead == [node]


def test_never_scraped_node_is_never_suspected():
    """Absence of telemetry is not death: a node whose health port was
    NEVER reachable (deploy problem, health=False rigs) must not
    escalate — fencing on it would revoke leases on pure silence."""
    tracker = NodeHealthTracker(suspect_after_ticks=1,
                                dead_after_ticks=2)
    node = "nh-unseen-node"
    for missed in range(1, 10):
        tracker.ingest({node: _feed(fresh=False, missed=missed)})
    assert tracker.state(node) == "healthy"


def test_recovery_needs_consecutive_clean_scrapes():
    tracker = NodeHealthTracker(suspect_after_ticks=1,
                                dead_after_ticks=10, recover_ticks=2)
    node = "nh-rec-node"
    tracker.ingest({node: _feed(fresh=True)})
    tracker.ingest({node: _feed(fresh=False, missed=1)})
    assert tracker.state(node) == "suspect"
    tracker.ingest({node: _feed(fresh=True)})
    assert tracker.state(node) == "suspect"     # hysteresis: 1 < 2
    tracker.ingest({node: _feed(fresh=True)})
    assert tracker.state(node) == "healthy"
    kinds = [e["kind"] for e in EVENTS.tail(200)
             if e.get("node") == node]
    assert kinds == ["node_suspect", "node_healthy"]


def test_flapping_port_cannot_complete_recovery_on_a_missed_scrape():
    """The recovery streak counts CLEAN scrapes only: a missed tick
    below the suspect threshold targets healthy but is not recovery
    evidence — hit/miss alternation must keep the node cordoned."""
    tracker = NodeHealthTracker(suspect_after_ticks=2,
                                dead_after_ticks=10, recover_ticks=2)
    node = "nh-flap-node"
    tracker.ingest({node: _feed(fresh=True)})
    tracker.ingest({node: _feed(fresh=False, missed=1)})
    tracker.ingest({node: _feed(fresh=False, missed=2)})
    assert tracker.state(node) == "suspect"
    for _ in range(4):      # fresh, missed, fresh, missed ...
        tracker.ingest({node: _feed(fresh=True)})
        assert tracker.state(node) == "suspect"
        tracker.ingest({node: _feed(fresh=False, missed=1)})
        assert tracker.state(node) == "suspect"
    tracker.ingest({node: _feed(fresh=True)})
    tracker.ingest({node: _feed(fresh=True)})
    assert tracker.state(node) == "healthy"     # 2 CONSECUTIVE


def test_draining_healthz_cordons_within_one_tick_and_fires_on_drain():
    drained = []
    tracker = NodeHealthTracker(on_drain=drained.append)
    node = "nh-drain-node"
    tracker.ingest({node: _feed(fresh=True)})
    tracker.ingest({node: _feed(fresh=True, healthz="draining")})
    assert tracker.state(node) == "draining"
    assert tracker.cordoned(node)
    assert drained == [node]


def test_notready_condition_corroborates_silence_into_dead():
    kube = FakeKubeClient()
    node_name = "nh-notready-node"
    node = make_tpu_node(name=node_name)
    node["status"]["conditions"] = [{"type": "Ready", "status": "False"}]
    kube.put_node(node)
    tracker = NodeHealthTracker(kube, suspect_after_ticks=2,
                                dead_after_ticks=50,
                                node_poll_interval_s=0.0)
    tracker.ingest({node_name: _feed(fresh=True)})
    tracker.ingest({node_name: _feed(fresh=False, missed=1)})
    assert tracker.state(node_name) == "suspect"   # k8s says NotReady
    tracker.ingest({node_name: _feed(fresh=False, missed=2)})
    # NotReady + enough missed scrapes: dead WITHOUT the full 50-tick
    # silence window
    assert tracker.state(node_name) == "dead"


def test_ready_node_veto_caps_silence_at_suspect(monkeypatch):
    """A silent WORKER on a node k8s recently saw Ready must cordon,
    never fence: a bad worker-image rollout (every health port down,
    every Node healthy) would otherwise fence the whole fleet's
    leases. The veto lapses with the Ready observation's freshness —
    a truly dead node stops heartbeating and Ready goes stale."""
    kube = FakeKubeClient()
    node_name = "nh-veto-node"
    node = make_tpu_node(name=node_name)
    node["status"]["conditions"] = [{"type": "Ready", "status": "True"}]
    kube.put_node(node)
    tracker = NodeHealthTracker(kube, suspect_after_ticks=2,
                                dead_after_ticks=4,
                                node_poll_interval_s=0.0)
    tracker.ingest({node_name: _feed(fresh=True)})
    for missed in range(1, 8):
        tracker.ingest({node_name: _feed(fresh=False, missed=missed)})
    assert tracker.state(node_name) == "suspect"    # vetoed, not dead
    assert tracker.cordoned(node_name)
    # the Ready evidence goes stale: the veto lapses and the dead
    # window applies
    monkeypatch.setattr(nodehealth, "READY_VETO_S", 0.0)
    tracker.ingest({node_name: _feed(fresh=False, missed=9)})
    assert tracker.state(node_name) == "dead"


def test_broker_tick_renotifies_dead_nodes_with_leases():
    """A fence that failed on a transient error (or a repair thread
    that died) must not strand dead-with-leases: the broker tick
    re-runs node-down handling for dead nodes still anchoring
    leases — idempotent all the way down."""
    broker = AttachBroker(FakeKubeClient(), BrokerConfig())
    broker.bind_node_health(lambda node: "dead"
                            if node == "nh-rnf-node" else "healthy")
    broker.leases.record("d", "p-rnf", "t", "normal", ["0"],
                         node="nh-rnf-node")
    broker.tick()
    assert broker.leases.get("d", "p-rnf") is None
    assert broker.fenced()[-1]["pod"] == "p-rnf"


def test_termination_taint_cordons_and_triggers_proactive_drain():
    kube = FakeKubeClient()
    node_name = "nh-taint-node"
    node = make_tpu_node(name=node_name)
    node["spec"] = {"taints": [
        {"key": consts.TERMINATION_TAINT_KEYS[0], "effect": "NoSchedule"}]}
    kube.put_node(node)
    drained = []
    tracker = NodeHealthTracker(kube, on_drain=drained.append,
                                node_poll_interval_s=0.0)
    tracker.ingest({node_name: _feed(fresh=True)})
    tracker.ingest({node_name: _feed(fresh=True)})
    assert tracker.state(node_name) == "suspect"
    assert tracker.cordoned(node_name)
    assert drained == [node_name]       # migration starts BEFORE death


def test_snapshot_and_enabled_knob():
    tracker = NodeHealthTracker()
    tracker.ingest({"nh-snap-node": _feed(fresh=True)})
    snap = tracker.snapshot()
    assert snap["enabled"] is True
    assert snap["nodes"]["nh-snap-node"]["state"] == "healthy"
    assert nodehealth.enabled({}) is True
    assert nodehealth.enabled({"TPU_NODE_HEALTH": "0"}) is False


# -- broker fencing seam -------------------------------------------------------

def _slave(owner, owner_ns, name, chips=2):
    return {
        "metadata": {"name": name, "namespace": "tpu-pool", "labels": {
            consts.SLAVE_POD_LABEL_KEY: consts.SLAVE_POD_LABEL_VALUE,
            consts.OWNER_POD_LABEL_KEY: owner,
            consts.OWNER_NAMESPACE_LABEL_KEY: owner_ns,
        }},
        "spec": {"containers": [{"name": "p", "resources": {
            "limits": {consts.TPU_RESOURCE_NAME: str(chips)}}}]},
        "status": {"phase": "Running"},
    }


def test_fence_lease_drops_lease_deletes_slaves_frees_quota():
    kube = FakeKubeClient()
    kube.put_pod(_slave("fence-pod", "fence-ns", "fence-pod-slave-pod-1"))
    broker = AttachBroker(kube, BrokerConfig(quotas={"fence-tenant": 2},
                                             pool_namespace="tpu-pool"))
    lease = broker.leases.record("fence-ns", "fence-pod", "fence-tenant",
                                 "normal", ["0", "1"], node="nh-f-node")
    before = REGISTRY.lease_fences.value(reason="node-dead")
    assert broker.fence_lease(lease, reason="node-dead") is True
    assert broker.leases.get("fence-ns", "fence-pod") is None
    assert broker.leases.tenant_usage("fence-tenant") == 0
    assert kube.list_pods("tpu-pool") == []     # cluster truth cleaned
    assert REGISTRY.lease_fences.value(reason="node-dead") == before + 1
    fences = [e for e in EVENTS.tail(100)
              if e["kind"] == "lease_fenced"
              and e.get("pod") == "fence-pod"]
    assert len(fences) == 1
    assert fences[0]["attrs"]["reason"] == "node-dead"
    assert broker.fenced()[-1]["pod"] == "fence-pod"
    # /brokerz carries the fenced list once a fence happened
    assert broker.snapshot()["fenced"][-1]["reason"] == "node-dead"
    # idempotence: the lease is gone — a second fence is a no-op
    assert broker.fence_lease(lease, reason="node-dead") is False
    assert REGISTRY.lease_fences.value(reason="node-dead") == before + 1


def test_handle_node_down_fences_singles_dead_only():
    broker = AttachBroker(FakeKubeClient(), BrokerConfig())
    broker.leases.record("d", "p-dead", "t", "normal", ["0"],
                         node="nh-hd-node")
    broker.leases.record("d", "p-other", "t", "normal", ["1"],
                         node="nh-hd-other")
    broker.handle_node_down("nh-hd-node", dead=False)    # draining
    assert broker.leases.get("d", "p-dead") is not None  # untouched
    broker.handle_node_down("nh-hd-node", dead=True)
    assert broker.leases.get("d", "p-dead") is None
    assert broker.leases.get("d", "p-other") is not None


def test_reaper_fences_expired_lease_on_dead_node_after_n_failures():
    kube = FakeKubeClient()
    calls = []
    broker = AttachBroker(kube, BrokerConfig(lease_ttl_s=0.001))
    broker.bind(lambda lease, cause, force: calls.append(cause)
                or "ERROR")
    broker.bind_node_health(lambda node: "dead"
                            if node == "nh-reap-node" else "healthy")
    broker.leases.record("d", "p-reap", "t", "normal", ["0"],
                         node="nh-reap-node", ttl_s=0.001)
    time.sleep(0.01)
    fenced_before = REGISTRY.lease_fences.value(reason="reap-unreachable")
    # drive the reap path directly: the tick's dead-node re-notify
    # would fence on sight (belt and braces — this test exercises the
    # reaper's OWN escape, the one that fires even if node-down
    # handling raced or failed)
    for _ in range(consts.REAP_FENCE_AFTER):
        lease = broker.leases.get("d", "p-reap")
        assert lease is not None
        # force-expire past the reap backoff the failure path applied
        lease.expires_at = time.monotonic() - 1.0
        broker._reap(lease)
    # N failed reaps against a dead node: fenced, not retried forever
    # (the fence lands ON the Nth failure, so exactly N detach attempts
    # were made and none after)
    assert broker.leases.get("d", "p-reap") is None
    assert len(calls) == consts.REAP_FENCE_AFTER
    assert REGISTRY.lease_fences.value(reason="reap-unreachable") \
        == fenced_before + 1


def test_reaper_keeps_backing_off_on_live_nodes():
    kube = FakeKubeClient()
    broker = AttachBroker(kube, BrokerConfig(lease_ttl_s=0.001))
    broker.bind(lambda lease, cause, force: "ERROR")
    broker.bind_node_health(lambda node: "healthy")
    broker.leases.record("d", "p-live", "t", "normal", ["0"],
                         node="nh-live-node", ttl_s=0.001)
    time.sleep(0.01)
    for _ in range(consts.REAP_FENCE_AFTER + 2):
        lease = broker.leases.get("d", "p-live")
        assert lease is not None, \
            "lease on a LIVE node must never be fenced by the reaper"
        lease.expires_at = time.monotonic() - 1.0
        broker.tick()
    assert broker.leases.get("d", "p-live") is not None


# -- worker-directory negative cache -------------------------------------------

def test_directory_negative_cache_fast_fails_after_consecutive_failures():
    kube = FakeKubeClient()
    kube.put_pod(worker_pod("nh-neg-node", "10.0.0.5"))
    directory = WorkerDirectory(kube, ttl_s=3600)
    assert directory.worker_target("nh-neg-node") == "10.0.0.5:1200"
    # transient blips below the threshold: every lookup still resolves
    for _ in range(WorkerDirectory.NEGATIVE_AFTER_FAILURES - 1):
        directory.invalidate("nh-neg-node")
        assert directory.worker_target("nh-neg-node") == "10.0.0.5:1200"
    # the threshold-crossing failure arms the quarantine: same dead
    # target now fast-fails without a dial
    directory.invalidate("nh-neg-node")
    with pytest.raises(WorkerNotFoundError):
        directory.worker_target("nh-neg-node")


def test_directory_negative_cache_clears_on_replaced_worker():
    kube = FakeKubeClient()
    kube.put_pod(worker_pod("nh-neg2-node", "10.0.0.5"))
    directory = WorkerDirectory(kube, ttl_s=3600)
    directory.MISS_REFRESH_INTERVAL_S = 0.0     # no rate-limit in-test
    directory.worker_target("nh-neg2-node")
    for _ in range(WorkerDirectory.NEGATIVE_AFTER_FAILURES):
        directory.invalidate("nh-neg2-node")
    with pytest.raises(WorkerNotFoundError):
        directory.worker_target("nh-neg2-node")
    # the worker pod is REPLACED (new IP): the failure history belongs
    # to the dead incarnation — resolution works immediately
    kube.delete_pod("kube-system", "w1")
    kube.put_pod(worker_pod("nh-neg2-node", "10.0.0.9"))
    assert directory.worker_target("nh-neg2-node") == "10.0.0.9:1200"
    with directory._lock:
        assert "nh-neg2-node" not in directory._negative


def test_directory_negative_window_expires_to_half_open():
    kube = FakeKubeClient()
    kube.put_pod(worker_pod("nh-neg3-node", "10.0.0.5"))
    directory = WorkerDirectory(kube, ttl_s=3600)
    directory.NEGATIVE_TTL_BASE_S = 0.02
    directory.worker_target("nh-neg3-node")
    for _ in range(WorkerDirectory.NEGATIVE_AFTER_FAILURES):
        directory.invalidate("nh-neg3-node")
    with pytest.raises(WorkerNotFoundError):
        directory.worker_target("nh-neg3-node")
    time.sleep(0.03)
    # window passed: one attempt goes through half-open
    assert directory.worker_target("nh-neg3-node") == "10.0.0.5:1200"


# -- tpumounterctl nodes + doctor ----------------------------------------------

_FLEETZ_DEAD = {
    "nodes": {"node-x": {"state": "stale", "missed_ticks": 9}},
    "node_health": {
        "enabled": True, "suspect_after_ticks": 2, "dead_after_ticks": 5,
        "nodes": {"node-x": {"state": "dead", "reason": "scrape-silence",
                             "missed_ticks": 9,
                             "since_unix": time.time() - 300}}},
}
_BROKERZ_DEAD = {
    "fenced": [{"namespace": "d", "pod": "p1", "tenant": "t",
                "chips": 2, "node": "node-x", "reason": "node-dead",
                "ts": 1.0}],
    "leases": {"leases": [{"namespace": "d", "pod": "p2",
                           "tenant": "t", "chips": 2,
                           "node": "node-x"}]},
    "queue": {"depth": {}, "oldest_age_s": 0.0, "waiters": []},
    "tenants": {},
}


def _stub_fetch(monkeypatch, fleetz, brokerz):
    from gpumounter_tpu import cli
    import json as json_mod

    def fake_fetch(master, path, timeout):
        if path.startswith("/fleetz"):
            return json_mod.dumps(fleetz)
        if path == "/brokerz":
            return json_mod.dumps(brokerz)
        if path == "/healthz":
            return '{"status": "ok"}'
        return "{}"

    monkeypatch.setattr(cli, "_fetch_text", fake_fetch)
    return cli


def test_cli_nodes_exits_nonzero_on_dead_with_leases(monkeypatch,
                                                     capsys):
    cli = _stub_fetch(monkeypatch, _FLEETZ_DEAD, _BROKERZ_DEAD)
    rc = cli.main(["--master", "http://unused", "nodes"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "node-x: DEAD" in out
    assert "DEAD WITH LIVE LEASES" in out
    assert "fenced: d/p1" in out


def test_cli_nodes_reports_disabled_subsystem(monkeypatch, capsys):
    cli = _stub_fetch(monkeypatch, {"nodes": {}}, {})
    rc = cli.main(["--master", "http://unused", "nodes"])
    assert rc == 0
    assert "disabled" in capsys.readouterr().out


def test_doctor_crits_dead_node_with_live_leases(monkeypatch, capsys):
    cli = _stub_fetch(monkeypatch, _FLEETZ_DEAD, _BROKERZ_DEAD)
    rc = cli.main(["--master", "http://unused", "doctor"])
    out = capsys.readouterr().out
    assert rc == cli.EXIT_DOCTOR_CRIT
    assert "DEAD node(s) still holding leases" in out


def test_doctor_warns_prolonged_suspect(monkeypatch, capsys):
    fleetz = {
        "nodes": {"node-y": {"state": "stale", "missed_ticks": 3}},
        "node_health": {
            "enabled": True,
            "nodes": {"node-y": {
                "state": "suspect", "reason": "scrape-silence",
                "missed_ticks": 3,
                "since_unix": time.time() - 300}}},
    }
    cli = _stub_fetch(monkeypatch, fleetz, {"queue": {"depth": {}},
                                            "leases": {"leases": []}})
    rc = cli.main(["--master", "http://unused", "doctor"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "suspect > 120s" in out


# -- byte-for-byte pins (the subsystem off / idle) -----------------------------

def test_node_health_off_removes_tracker_and_fleetz_section(monkeypatch,
                                                            fake_host):
    monkeypatch.setenv(consts.ENV_NODE_HEALTH, "0")
    from gpumounter_tpu.master.discovery import WorkerDirectory as WD
    from gpumounter_tpu.master.gateway import MasterGateway
    kube = FakeKubeClient()
    gateway = MasterGateway(kube, WD(kube))
    assert gateway.nodehealth is None
    assert gateway.fleet.node_health is None
    snap = gateway.fleet.snapshot()
    assert "node_health" not in snap
    assert gateway.broker._node_health_fn is None
    assert "fenced" not in gateway.broker.snapshot()


def test_node_health_on_but_idle_keeps_payloads_byte_for_byte(
        monkeypatch):
    """Default-on with nothing unhealthy: /fleetz gains its (empty)
    node_health section, but /brokerz and the attach path carry ZERO
    new keys, events or series — the fault-free path is unchanged."""
    monkeypatch.delenv(consts.ENV_NODE_HEALTH, raising=False)
    from gpumounter_tpu.master.discovery import WorkerDirectory as WD
    from gpumounter_tpu.master.gateway import MasterGateway
    kube = FakeKubeClient()
    gateway = MasterGateway(kube, WD(kube))
    assert gateway.nodehealth is not None
    assert "fenced" not in gateway.broker.snapshot()
    monkeypatch.setenv(consts.ENV_NODE_HEALTH, "0")
    gateway_off = MasterGateway(kube, WD(kube))
    on = gateway.broker.snapshot()
    off = gateway_off.broker.snapshot()
    assert on == off
