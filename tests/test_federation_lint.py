"""AST lints pinning the re-federation protocol's two structural
contracts (ISSUE 15 CI satellite):

1. **No restore path deletes a checkpoint.** In jaxcheck/drain.py every
   deletion call (unlink/remove/rmtree/rmdir) is confined to the commit
   path (`_prune_generations`, reached only from `commit_manifest`,
   which runs strictly AFTER the new generation's manifest + LATEST are
   durable) and the atomic-writer's failed-tmp cleanup. A deletion
   reachable from a restore function could destroy the sole surviving
   copy of the state exactly when it is needed.

2. **Every barrier transition is observable.** In master/slicetxn.py
   the `tpumounter_slice_barriers_total` metric and the `slice_barrier`
   event are emitted ONLY inside `_barrier_transition` (which emits
   BOTH — the pairing), and every method that mutates the barrier map
   crosses that seam. A silent transition would blind the doctor's
   stuck-barrier check precisely when a member died mid-resize.
"""

import ast
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parse(rel):
    path = os.path.join(ROOT, "gpumounter_tpu", rel)
    with open(path) as f:
        return ast.parse(f.read(), filename=path)


def _functions(tree):
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _call_names(node):
    """Dotted names of every call inside ``node`` (e.g. "os.unlink",
    "self._barrier_transition", "shutil.rmtree")."""
    names = []
    for call in ast.walk(node):
        if not isinstance(call, ast.Call):
            continue
        parts = []
        f = call.func
        while isinstance(f, ast.Attribute):
            parts.append(f.attr)
            f = f.value
        if isinstance(f, ast.Name):
            parts.append(f.id)
        names.append(".".join(reversed(parts)))
    return names


_DELETERS = {"os.unlink", "os.remove", "os.rmdir", "shutil.rmtree"}


def test_drain_deletions_confined_to_the_commit_path():
    tree = _parse("jaxcheck/drain.py")
    allowed = {
        "_prune_generations",     # THE pruning seam (commit-only)
        "_atomic_write",          # failed-tmp cleanup inside the writer
        "drain_restore_cycle",    # legacy helper deleting its OWN tmp
    }
    offenders = {}
    for name, defs in _functions(tree).items():
        for fn in defs:
            hits = [c for c in _call_names(fn) if c in _DELETERS]
            if hits and name not in allowed:
                offenders[name] = hits
    assert not offenders, (
        f"deletion calls outside the sanctioned commit path: "
        f"{offenders} — a restore path that deletes can destroy the "
        "sole surviving checkpoint")


def test_prune_reached_only_from_commit():
    tree = _parse("jaxcheck/drain.py")
    callers = []
    for name, defs in _functions(tree).items():
        for fn in defs:
            if name == "_prune_generations":
                continue
            if any(c.endswith("_prune_generations")
                   for c in _call_names(fn)):
                callers.append(name)
    assert callers == ["commit_manifest"], (
        f"_prune_generations called from {callers}; pruning may run "
        "ONLY inside the commit (after manifest + LATEST are durable)")


def test_restore_paths_exist_and_never_delete():
    """The concrete restore-path functions (belt to the braces above:
    they must exist, or the allowlist lint is vacuously green)."""
    tree = _parse("jaxcheck/drain.py")
    functions = _functions(tree)
    for required in ("restore_sharded", "restore_last_good",
                     "_load_generation", "_verify_shards", "restore"):
        assert required in functions, f"missing {required}"
        for fn in functions[required]:
            assert not any(c in _DELETERS for c in _call_names(fn))


def test_federation_module_never_deletes_checkpoints():
    tree = _parse("jaxcheck/federation.py")
    hits = [c for c in _call_names(tree) if c in _DELETERS]
    assert hits == [], (
        f"jaxcheck/federation.py deletes files: {hits} — the member "
        "side owns no checkpoint lifecycle; deletion is the commit "
        "path's alone")


def test_barrier_metric_and_event_only_inside_the_seam():
    tree = _parse("master/slicetxn.py")
    functions = _functions(tree)
    offenders = []
    for name, defs in functions.items():
        for fn in defs:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _call_names(node)[:1]
                if dotted == ["REGISTRY.slice_barriers.inc"] \
                        and name != "_barrier_transition":
                    offenders.append((name, "metric"))
                if dotted == ["EVENTS.emit"] and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        node.args[0].value == "slice_barrier" and \
                        name != "_barrier_transition":
                    offenders.append((name, "event"))
    assert not offenders, (
        f"barrier metric/event emitted outside _barrier_transition: "
        f"{offenders}")


def test_barrier_seam_pairs_metric_with_event():
    tree = _parse("master/slicetxn.py")
    seam = _functions(tree).get("_barrier_transition")
    assert seam, "slicetxn.py lost _barrier_transition"
    calls = _call_names(seam[0])
    assert "REGISTRY.slice_barriers.inc" in calls
    assert "EVENTS.emit" in calls


def test_every_barrier_map_mutation_crosses_the_seam():
    """Any method that writes self._barriers (arm, drop, …) must call
    _barrier_transition somewhere in its body — no silent barrier
    state changes."""
    tree = _parse("master/slicetxn.py")
    offenders = []
    for name, defs in _functions(tree).items():
        for fn in defs:
            mutates = False
            for node in ast.walk(fn):
                # self._barriers[...] = ... / del self._barriers[...]
                if isinstance(node, (ast.Assign, ast.Delete)):
                    targets = node.targets
                    for target in targets:
                        if isinstance(target, ast.Subscript) and \
                                isinstance(target.value,
                                           ast.Attribute) and \
                                target.value.attr == "_barriers":
                            mutates = True
                # self._barriers.pop(...) / .clear() / .setdefault()
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("pop", "clear",
                                           "setdefault", "update") and \
                        isinstance(node.func.value, ast.Attribute) and \
                        node.func.value.attr == "_barriers":
                    mutates = True
            if mutates and "self._barrier_transition" not in \
                    _call_names(fn):
                offenders.append(name)
    assert not offenders, (
        f"methods mutate self._barriers without crossing "
        f"_barrier_transition: {offenders}")


def test_barrier_route_registered():
    path = os.path.join(ROOT, "gpumounter_tpu", "master", "gateway.py")
    source = open(path).read()
    assert '"/slice/barrier": "slicebarrier"' in source
    assert '"slicebarrier"' in source.split("_UNTRACED_ROUTES")[1] \
        .split("}")[0], "barrier polling must stay out of the trace ring"


def test_barrier_timeout_knob_is_plumbed_and_validated():
    from gpumounter_tpu.master.admission import BrokerConfig
    from gpumounter_tpu.utils import consts
    from gpumounter_tpu.utils.config import Settings
    assert consts.DEFAULT_RESIZE_BARRIER_TIMEOUT_S > 0
    assert BrokerConfig().resize_barrier_timeout_s == \
        consts.DEFAULT_RESIZE_BARRIER_TIMEOUT_S
    s = Settings.from_env({consts.ENV_RESIZE_BARRIER_TIMEOUT_S: "45"})
    assert s.resize_barrier_timeout_s == 45.0
    assert BrokerConfig.from_settings(s).resize_barrier_timeout_s == 45.0
    with pytest.raises(ValueError):
        Settings.from_env({consts.ENV_RESIZE_BARRIER_TIMEOUT_S: "0"})
