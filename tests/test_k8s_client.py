"""Tests for the minimal k8s client: FakeKubeClient semantics, and
InClusterKubeClient wire behaviour against a stub apiserver speaking plain
HTTP (list/get/create/delete/watch streaming)."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gpumounter_tpu.k8s.client import FakeKubeClient, InClusterKubeClient
from gpumounter_tpu.utils.errors import K8sApiError, PodNotFoundError


def make_pod(name, namespace="default", labels=None, phase="Pending"):
    return {
        "metadata": {"name": name, "namespace": namespace,
                     "labels": labels or {}},
        "spec": {},
        "status": {"phase": phase},
    }


# -- FakeKubeClient ------------------------------------------------------------


def test_fake_get_missing_raises():
    c = FakeKubeClient()
    with pytest.raises(PodNotFoundError):
        c.get_pod("default", "nope")


def test_fake_create_get_list_delete():
    c = FakeKubeClient()
    c.create_pod("default", make_pod("p1", labels={"app": "x"}))
    c.create_pod("default", make_pod("p2", labels={"app": "y"}))
    assert c.get_pod("default", "p1")["metadata"]["name"] == "p1"
    assert len(c.list_pods("default")) == 2
    assert [p["metadata"]["name"]
            for p in c.list_pods("default", label_selector="app=x")] == ["p1"]
    c.delete_pod("default", "p1")
    with pytest.raises(PodNotFoundError):
        c.get_pod("default", "p1")
    c.delete_pod("default", "p1")  # idempotent


def test_fake_duplicate_create_conflicts():
    c = FakeKubeClient()
    c.create_pod("default", make_pod("p1"))
    with pytest.raises(K8sApiError):
        c.create_pod("default", make_pod("p1"))


def test_fake_on_create_hook_mutates_async():
    c = FakeKubeClient()

    def scheduler(pod):
        time.sleep(0.02)
        c.set_pod_status(pod["metadata"]["namespace"],
                         pod["metadata"]["name"], phase="Running")

    c.on_create.append(scheduler)
    c.create_pod("default", make_pod("p1"))
    assert c.get_pod("default", "p1")["status"]["phase"] == "Pending"
    deadline = time.time() + 2
    while time.time() < deadline:
        if c.get_pod("default", "p1")["status"]["phase"] == "Running":
            break
        time.sleep(0.01)
    assert c.get_pod("default", "p1")["status"]["phase"] == "Running"


def test_fake_watch_sees_past_and_future_events():
    c = FakeKubeClient()
    c.create_pod("default", make_pod("p1"))

    seen = []

    def consume():
        for event_type, pod in c.watch_pods("default", timeout_s=2.0):
            seen.append((event_type, pod["metadata"]["name"],
                         pod["status"]["phase"]))
            if event_type == "MODIFIED":
                return

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)
    c.set_pod_status("default", "p1", phase="Running")
    t.join(timeout=3)
    assert not t.is_alive()
    assert ("ADDED", "p1", "Pending") in seen
    assert ("MODIFIED", "p1", "Running") in seen


def test_fake_watch_times_out():
    c = FakeKubeClient()
    start = time.monotonic()
    events = list(c.watch_pods("default", timeout_s=0.2))
    assert events == []
    assert time.monotonic() - start < 2.0


def test_fake_watch_field_selector():
    c = FakeKubeClient()
    c.create_pod("default", make_pod("p1"))
    c.create_pod("default", make_pod("p2"))
    events = list(c.watch_pods("default",
                               field_selector="metadata.name=p2",
                               timeout_s=0.2))
    assert [name for _, pod in events
            for name in [pod["metadata"]["name"]]] == ["p2"]


# -- InClusterKubeClient against a stub apiserver ------------------------------


class _StubApiserver(BaseHTTPRequestHandler):
    pods = {}          # (ns, name) -> pod
    requests_log = []

    def log_message(self, *args):
        pass

    def _send_json(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        type(self).requests_log.append(("GET", self.path,
                                        self.headers.get("Authorization")))
        parts = self.path.split("?")[0].strip("/").split("/")
        # /api/v1/namespaces/<ns>/pods[/<name>]
        if "watch=true" in self.path:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            for (ns, name), pod in type(self).pods.items():
                if ns == parts[3]:
                    line = json.dumps(
                        {"type": "ADDED", "object": pod}) + "\n"
                    self.wfile.write(line.encode())
            return
        if len(parts) == 6:
            pod = type(self).pods.get((parts[3], parts[5]))
            if pod is None:
                self._send_json(404, {"message": "not found"})
            else:
                self._send_json(200, pod)
        else:
            items = [p for (ns, _), p in type(self).pods.items()
                     if ns == parts[3]]
            self._send_json(200, {"items": items})

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        pod = json.loads(self.rfile.read(length))
        ns = self.path.strip("/").split("/")[3]
        type(self).pods[(ns, pod["metadata"]["name"])] = pod
        self._send_json(201, pod)

    def do_DELETE(self):
        parts = self.path.strip("/").split("/")
        type(self).pods.pop((parts[3], parts[5]), None)
        self._send_json(200, {"status": "Success"})


@pytest.fixture
def stub_apiserver(tmp_path):
    _StubApiserver.pods = {}
    _StubApiserver.requests_log = []
    server = ThreadingHTTPServer(("127.0.0.1", 0), _StubApiserver)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    sa = tmp_path / "sa"
    sa.mkdir()
    (sa / "token").write_text("test-token")
    client = InClusterKubeClient(
        host=f"http://127.0.0.1:{server.server_port}", sa_dir=str(sa))
    yield client
    server.shutdown()


def test_incluster_crud_roundtrip(stub_apiserver):
    c = stub_apiserver
    c.create_pod("default", make_pod("p1"))
    assert c.get_pod("default", "p1")["metadata"]["name"] == "p1"
    assert len(c.list_pods("default")) == 1
    c.delete_pod("default", "p1")
    with pytest.raises(PodNotFoundError) as ei:
        c.get_pod("default", "p1")
    assert ei.value.namespace == "default"
    c.delete_pod("default", "p1")  # 404 swallowed


def test_incluster_sends_bearer_token(stub_apiserver):
    c = stub_apiserver
    c.list_pods("default")
    auths = [a for (_, _, a) in _StubApiserver.requests_log]
    assert "Bearer test-token" in auths


def test_incluster_watch_stream(stub_apiserver):
    c = stub_apiserver
    c.create_pod("default", make_pod("p1", phase="Running"))
    events = list(c.watch_pods("default", timeout_s=2))
    assert events and events[0][0] == "ADDED"
    assert events[0][1]["metadata"]["name"] == "p1"


def test_incluster_requires_env_when_no_host():
    import os
    old = os.environ.pop("KUBERNETES_SERVICE_HOST", None)
    try:
        with pytest.raises(K8sApiError):
            InClusterKubeClient()
    finally:
        if old is not None:
            os.environ["KUBERNETES_SERVICE_HOST"] = old


def test_fake_list_version_seeds_watch_resume():
    """watch_pods(resource_version=rv_from_list) delivers exactly the events
    recorded after the LIST — the no-lost-event contract the allocator's
    wait loops rely on."""
    kube = FakeKubeClient()
    kube.put_pod({"metadata": {"name": "a", "namespace": "ns"},
                  "status": {"phase": "Pending"}})
    pods, rv = kube.list_pods_with_version("ns")
    assert len(pods) == 1 and rv == "1"
    kube.set_pod_status("ns", "a", phase="Running")       # event after LIST
    events = list(kube.watch_pods("ns", timeout_s=0.3, resource_version=rv))
    assert [(t, p["status"]["phase"]) for t, p in events] == \
        [("MODIFIED", "Running")]
    # each event object carries its resourceVersion like a real apiserver
    assert events[0][1]["metadata"]["resourceVersion"] == "2"
    # and a fresh watch without a version still replays history
    all_events = list(kube.watch_pods("ns", timeout_s=0.3))
    assert len(all_events) == 2
