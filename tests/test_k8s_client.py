"""Tests for the minimal k8s client: FakeKubeClient semantics, and
InClusterKubeClient wire behaviour against a stub apiserver speaking plain
HTTP (list/get/create/delete/watch streaming)."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gpumounter_tpu.k8s.client import (FakeKubeClient, InClusterKubeClient,
                                        KubeconfigKubeClient)
from gpumounter_tpu.utils.errors import K8sApiError, PodNotFoundError


def make_pod(name, namespace="default", labels=None, phase="Pending"):
    return {
        "metadata": {"name": name, "namespace": namespace,
                     "labels": labels or {}},
        "spec": {},
        "status": {"phase": phase},
    }


# -- FakeKubeClient ------------------------------------------------------------


def test_fake_get_missing_raises():
    c = FakeKubeClient()
    with pytest.raises(PodNotFoundError):
        c.get_pod("default", "nope")


def test_fake_create_get_list_delete():
    c = FakeKubeClient()
    c.create_pod("default", make_pod("p1", labels={"app": "x"}))
    c.create_pod("default", make_pod("p2", labels={"app": "y"}))
    assert c.get_pod("default", "p1")["metadata"]["name"] == "p1"
    assert len(c.list_pods("default")) == 2
    assert [p["metadata"]["name"]
            for p in c.list_pods("default", label_selector="app=x")] == ["p1"]
    c.delete_pod("default", "p1")
    with pytest.raises(PodNotFoundError):
        c.get_pod("default", "p1")
    c.delete_pod("default", "p1")  # idempotent


def test_fake_duplicate_create_conflicts():
    c = FakeKubeClient()
    c.create_pod("default", make_pod("p1"))
    with pytest.raises(K8sApiError):
        c.create_pod("default", make_pod("p1"))


def test_fake_on_create_hook_mutates_async():
    c = FakeKubeClient()

    def scheduler(pod):
        time.sleep(0.02)
        c.set_pod_status(pod["metadata"]["namespace"],
                         pod["metadata"]["name"], phase="Running")

    c.on_create.append(scheduler)
    c.create_pod("default", make_pod("p1"))
    assert c.get_pod("default", "p1")["status"]["phase"] == "Pending"
    deadline = time.time() + 2
    while time.time() < deadline:
        if c.get_pod("default", "p1")["status"]["phase"] == "Running":
            break
        time.sleep(0.01)
    assert c.get_pod("default", "p1")["status"]["phase"] == "Running"


def test_fake_watch_sees_past_and_future_events():
    c = FakeKubeClient()
    c.create_pod("default", make_pod("p1"))

    seen = []

    def consume():
        for event_type, pod in c.watch_pods("default", timeout_s=2.0):
            seen.append((event_type, pod["metadata"]["name"],
                         pod["status"]["phase"]))
            if event_type == "MODIFIED":
                return

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)
    c.set_pod_status("default", "p1", phase="Running")
    t.join(timeout=3)
    assert not t.is_alive()
    assert ("ADDED", "p1", "Pending") in seen
    assert ("MODIFIED", "p1", "Running") in seen


def test_fake_watch_times_out():
    c = FakeKubeClient()
    start = time.monotonic()
    events = list(c.watch_pods("default", timeout_s=0.2))
    assert events == []
    assert time.monotonic() - start < 2.0


def test_fake_watch_field_selector():
    c = FakeKubeClient()
    c.create_pod("default", make_pod("p1"))
    c.create_pod("default", make_pod("p2"))
    events = list(c.watch_pods("default",
                               field_selector="metadata.name=p2",
                               timeout_s=0.2))
    assert [name for _, pod in events
            for name in [pod["metadata"]["name"]]] == ["p2"]


# -- InClusterKubeClient against a stub apiserver ------------------------------


class _StubApiserver(BaseHTTPRequestHandler):
    pods = {}          # (ns, name) -> pod
    requests_log = []

    def log_message(self, *args):
        pass

    def _send_json(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        type(self).requests_log.append(("GET", self.path,
                                        self.headers.get("Authorization")))
        parts = self.path.split("?")[0].strip("/").split("/")
        # /api/v1/namespaces/<ns>/pods[/<name>]
        if "watch=true" in self.path:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            for (ns, name), pod in type(self).pods.items():
                if ns == parts[3]:
                    line = json.dumps(
                        {"type": "ADDED", "object": pod}) + "\n"
                    self.wfile.write(line.encode())
            return
        if len(parts) == 6:
            pod = type(self).pods.get((parts[3], parts[5]))
            if pod is None:
                self._send_json(404, {"message": "not found"})
            else:
                self._send_json(200, pod)
        else:
            items = [p for (ns, _), p in type(self).pods.items()
                     if ns == parts[3]]
            self._send_json(200, {"items": items})

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        pod = json.loads(self.rfile.read(length))
        ns = self.path.strip("/").split("/")[3]
        type(self).pods[(ns, pod["metadata"]["name"])] = pod
        self._send_json(201, pod)

    def do_DELETE(self):
        parts = self.path.strip("/").split("/")
        type(self).pods.pop((parts[3], parts[5]), None)
        self._send_json(200, {"status": "Success"})


@pytest.fixture
def stub_http_server():
    _StubApiserver.pods = {}
    _StubApiserver.requests_log = []
    server = ThreadingHTTPServer(("127.0.0.1", 0), _StubApiserver)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_port}"
    server.shutdown()


@pytest.fixture
def stub_apiserver(tmp_path, stub_http_server):
    sa = tmp_path / "sa"
    sa.mkdir()
    (sa / "token").write_text("test-token")
    yield InClusterKubeClient(host=stub_http_server, sa_dir=str(sa))


def test_incluster_crud_roundtrip(stub_apiserver):
    c = stub_apiserver
    c.create_pod("default", make_pod("p1"))
    assert c.get_pod("default", "p1")["metadata"]["name"] == "p1"
    assert len(c.list_pods("default")) == 1
    c.delete_pod("default", "p1")
    with pytest.raises(PodNotFoundError) as ei:
        c.get_pod("default", "p1")
    assert ei.value.namespace == "default"
    c.delete_pod("default", "p1")  # 404 swallowed


def test_incluster_sends_bearer_token(stub_apiserver):
    c = stub_apiserver
    c.list_pods("default")
    auths = [a for (_, _, a) in _StubApiserver.requests_log]
    assert "Bearer test-token" in auths


def test_incluster_watch_stream(stub_apiserver):
    c = stub_apiserver
    c.create_pod("default", make_pod("p1", phase="Running"))
    events = list(c.watch_pods("default", timeout_s=2))
    assert events and events[0][0] == "ADDED"
    assert events[0][1]["metadata"]["name"] == "p1"


def test_incluster_requires_env_when_no_host():
    import os
    old = os.environ.pop("KUBERNETES_SERVICE_HOST", None)
    try:
        with pytest.raises(K8sApiError):
            InClusterKubeClient()
    finally:
        if old is not None:
            os.environ["KUBERNETES_SERVICE_HOST"] = old


def test_fake_list_version_seeds_watch_resume():
    """watch_pods(resource_version=rv_from_list) delivers exactly the events
    recorded after the LIST — the no-lost-event contract the allocator's
    wait loops rely on."""
    kube = FakeKubeClient()
    kube.put_pod({"metadata": {"name": "a", "namespace": "ns"},
                  "status": {"phase": "Pending"}})
    pods, rv = kube.list_pods_with_version("ns")
    assert len(pods) == 1 and rv == "1"
    kube.set_pod_status("ns", "a", phase="Running")       # event after LIST
    events = list(kube.watch_pods("ns", timeout_s=0.3, resource_version=rv))
    assert [(t, p["status"]["phase"]) for t, p in events] == \
        [("MODIFIED", "Running")]
    # each event object carries its resourceVersion like a real apiserver
    assert events[0][1]["metadata"]["resourceVersion"] == "2"
    # and a fresh watch without a version still replays history
    all_events = list(kube.watch_pods("ns", timeout_s=0.3))
    assert len(all_events) == 2


# -- KubeconfigKubeClient ------------------------------------------------------


def _write_kubeconfig(tmp_path, server, user=None, cluster_extra=None,
                      name="kc"):
    import yaml
    cfg = {
        "apiVersion": "v1", "kind": "Config",
        "current-context": "dev",
        "contexts": [{"name": "dev",
                      "context": {"cluster": "c1", "user": "u1",
                                  "namespace": "tpu-pool"}},
                     {"name": "other",
                      "context": {"cluster": "c1", "user": "u1"}}],
        "clusters": [{"name": "c1",
                      "cluster": {"server": server,
                                  **(cluster_extra or {})}}],
        "users": [{"name": "u1", "user": user or {}}],
    }
    p = tmp_path / name
    p.write_text(yaml.safe_dump(cfg))
    return str(p)


def test_kubeconfig_token_crud_and_bearer(tmp_path, stub_http_server):
    path = _write_kubeconfig(tmp_path, stub_http_server,
                             user={"token": "kc-token"})
    c = KubeconfigKubeClient(path=path)
    assert c.context_name == "dev"
    assert c.namespace == "tpu-pool"
    c.create_pod("default", make_pod("p1"))
    assert c.get_pod("default", "p1")["metadata"]["name"] == "p1"
    c.delete_pod("default", "p1")
    with pytest.raises(PodNotFoundError):
        c.get_pod("default", "p1")
    auths = [a for (_, _, a) in _StubApiserver.requests_log]
    assert "Bearer kc-token" in auths


def test_kubeconfig_token_file(tmp_path, stub_http_server):
    tok = tmp_path / "tok"
    tok.write_text("file-token\n")
    path = _write_kubeconfig(tmp_path, stub_http_server,
                             user={"tokenFile": str(tok)})
    c = KubeconfigKubeClient(path=path)
    c.list_pods("default")
    auths = [a for (_, _, a) in _StubApiserver.requests_log]
    assert "Bearer file-token" in auths


def test_kubeconfig_explicit_context_and_env(tmp_path, stub_http_server,
                                             monkeypatch):
    path = _write_kubeconfig(tmp_path, stub_http_server,
                             user={"token": "t"})
    c = KubeconfigKubeClient(path=path, context="other")
    assert c.context_name == "other"
    assert c.namespace == "default"   # context without explicit namespace
    monkeypatch.setenv("KUBECONFIG", path)
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    from gpumounter_tpu.k8s.client import default_kube_client
    assert isinstance(default_kube_client(), KubeconfigKubeClient)


def test_kubeconfig_error_paths(tmp_path, stub_http_server):
    with pytest.raises(K8sApiError, match="unreadable"):
        KubeconfigKubeClient(path=str(tmp_path / "absent"))
    path = _write_kubeconfig(tmp_path, stub_http_server, user={"token": "t"})
    with pytest.raises(K8sApiError, match="no entry named"):
        KubeconfigKubeClient(path=path, context="missing")
    path2 = _write_kubeconfig(
        tmp_path, stub_http_server,
        user={"exec": {"command": "gke-gcloud-auth-plugin"}}, name="kc-exec")
    with pytest.raises(K8sApiError, match="exec"):
        KubeconfigKubeClient(path=path2)


def test_kubeconfig_inline_ca_data_builds_tls_context(tmp_path):
    """https server + inline base64 CA: the ssl context must be built from
    the decoded bytes (materialised to a temp file)."""
    import base64
    import datetime
    # A self-signed cert is overkill to mint without the cryptography lib;
    # instead assert the CA plumbing by pointing at a PEM we generate with
    # ssl's own machinery is unavailable — so use a pre-baked minimal PEM
    # that create_default_context accepts as an (empty-CN) root.
    pytest.importorskip("cryptography")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    subject = x509.Name(
        [x509.NameAttribute(x509.NameOID.COMMON_NAME, "test-ca")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(subject).issuer_name(subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    pem = cert.public_bytes(serialization.Encoding.PEM)
    path = _write_kubeconfig(
        tmp_path, "https://127.0.0.1:9",
        user={"token": "t"},
        cluster_extra={
            "certificate-authority-data":
                base64.b64encode(pem).decode()})
    c = KubeconfigKubeClient(path=path)
    assert c._ssl is not None
    # the CA made it into the context's store
    ders = c._ssl.get_ca_certs(binary_form=True)
    assert any(
        cert.public_bytes(serialization.Encoding.DER) == d for d in ders)


def test_kubeconfig_tokenfile_unreadable_raises(tmp_path, stub_http_server):
    path = _write_kubeconfig(tmp_path, stub_http_server,
                             user={"tokenFile": str(tmp_path / "rotated")})
    c = KubeconfigKubeClient(path=path)
    with pytest.raises(K8sApiError, match="tokenFile unreadable"):
        c.list_pods("default")


def test_kubeconfig_bad_yaml_and_bad_b64_are_typed(tmp_path):
    p = tmp_path / "broken"
    p.write_text("{unclosed: [")
    with pytest.raises(K8sApiError, match="unparseable"):
        KubeconfigKubeClient(path=str(p))
    path = _write_kubeconfig(tmp_path, "https://127.0.0.1:9",
                             user={"token": "t"},
                             cluster_extra={
                                 "certificate-authority-data": "!!!notb64"})
    with pytest.raises(K8sApiError, match="base64"):
        KubeconfigKubeClient(path=path)


def test_kubeconfig_env_colon_separated_list(tmp_path, stub_http_server,
                                             monkeypatch):
    real = _write_kubeconfig(tmp_path, stub_http_server,
                             user={"token": "t"}, name="real")
    monkeypatch.setenv("KUBECONFIG",
                       f"{tmp_path / 'missing'}:{real}")
    c = KubeconfigKubeClient()
    assert c.context_name == "dev"


def test_kubeconfig_inline_key_tempfile_is_deleted(tmp_path, monkeypatch):
    """Inline client-key-data must not persist on disk after construction."""
    import base64
    import glob
    import tempfile
    monkeypatch.setattr(tempfile, "tempdir", str(tmp_path))
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())
    # Key data without a client-certificate is rejected fail-closed
    # (client-go parity: unpaired cert/key errors) — the temp file is
    # still created during construction and must still be cleaned up.
    path = _write_kubeconfig(
        tmp_path, "https://127.0.0.1:9",
        user={"token": "t",
              "client-key-data": base64.b64encode(key_pem).decode()})
    with pytest.raises(K8sApiError, match="client-key"):
        KubeconfigKubeClient(path=path)
    assert glob.glob(str(tmp_path / "kubeconfig-client-key-*")) == []


def test_default_client_kubeconfig_env_beats_incluster(tmp_path,
                                                       stub_http_server,
                                                       monkeypatch):
    """Every in-cluster pod has KUBERNETES_SERVICE_HOST injected; an
    explicitly set $KUBECONFIG must still win (controller-runtime chain)."""
    from gpumounter_tpu.k8s.client import default_kube_client
    path = _write_kubeconfig(tmp_path, stub_http_server, user={"token": "t"})
    monkeypatch.setenv("KUBECONFIG", path)
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    assert isinstance(default_kube_client(), KubeconfigKubeClient)


def test_kubeconfig_tokenfile_relative_to_config_dir(tmp_path,
                                                     stub_http_server):
    (tmp_path / "token.txt").write_text("rel-token")
    path = _write_kubeconfig(tmp_path, stub_http_server,
                             user={"tokenFile": "token.txt"})
    c = KubeconfigKubeClient(path=path)
    c.list_pods("default")
    auths = [a for (_, _, a) in _StubApiserver.requests_log]
    assert "Bearer rel-token" in auths


def test_kubeconfig_missing_ca_file_is_typed(tmp_path):
    path = _write_kubeconfig(
        tmp_path, "https://127.0.0.1:9", user={"token": "t"},
        cluster_extra={"certificate-authority": "/etc/absent-ca.crt"})
    with pytest.raises(K8sApiError, match="TLS material"):
        KubeconfigKubeClient(path=path)
