"""Attach-broker suite (master/admission.py + master/lease.py): quota
admission (429 + Retry-After), the contention queue's priority-then-fair
completion order, high-priority preemption of over-quota tenants, lease
expiry/renewal, and master-restart re-derivation from cluster ground
truth with zero double-actuation."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from gpumounter_tpu.k8s.client import FakeKubeClient
from gpumounter_tpu.master.admission import AttachBroker, BrokerConfig
from gpumounter_tpu.master.discovery import WorkerDirectory
from gpumounter_tpu.master.gateway import MasterGateway
from gpumounter_tpu.testing.chaos import (assert_broker_invariants,
                                          wait_events_drained)
from gpumounter_tpu.utils.metrics import REGISTRY
from gpumounter_tpu.worker.grpc_server import build_server

from tests.helpers import WorkerRig, worker_pod


class BrokerStack:
    """WorkerRig + live gRPC worker + gateway over the rig's OWN fake
    cluster (shared view: the broker's re-derivation and the preemption
    victim scan see the worker's slave pods)."""

    def __init__(self, fake_host, config=None, n_chips=4, extra_pods=(),
                 **rig_kwargs):
        self.rig = WorkerRig(fake_host, n_chips=n_chips, **rig_kwargs)
        self.server, self.port = build_server(self.rig.service, port=0,
                                              address="127.0.0.1")
        self.server.start()
        self.kube = self.rig.sim.kube
        self.kube.put_pod(worker_pod("node-a", "127.0.0.1"))
        for name in extra_pods:
            pod = self.rig.sim.add_target_pod(name=name)
            self.rig.provision_container(pod)
        self.gateway = self.new_gateway(config)

    def new_gateway(self, config=None) -> MasterGateway:
        """A fresh master over the same cluster — the "restart"."""
        broker = AttachBroker(self.kube, config or BrokerConfig())
        return MasterGateway(self.kube,
                             WorkerDirectory(self.kube,
                                             grpc_port=self.port),
                             broker=broker)

    def close(self):
        self.server.stop(grace=0)
        self.rig.close()


@pytest.fixture
def stack_factory(fake_host):
    stacks = []

    def make(**kwargs) -> BrokerStack:
        stack = BrokerStack(fake_host, **kwargs)
        stacks.append(stack)
        return stack

    yield make
    for stack in stacks:
        stack.close()


def add(gw, pod, n=2, entire=False, tenant=None, priority=None, rid=None,
        ns="default"):
    params = []
    if tenant:
        params.append(f"tenant={tenant}")
    if priority:
        params.append(f"priority={priority}")
    path = (f"/addtpu/namespace/{ns}/pod/{pod}/tpu/{n}"
            f"/isEntireMount/{'true' if entire else 'false'}")
    if params:
        path += "?" + "&".join(params)
    headers = {"X-Request-Id": rid} if rid else None
    return gw.handle("GET", path, headers=headers)


def remove(gw, pod, uuids=None, force=False, ns="default"):
    body = json.dumps({"uuids": uuids or []}).encode()
    return gw.handle(
        "POST", f"/removetpu/namespace/{ns}/pod/{pod}"
                f"/force/{'true' if force else 'false'}", body)


# -- admission: quotas ---------------------------------------------------------

def test_over_quota_attach_429_with_retry_hint(stack_factory):
    stack = stack_factory(config=BrokerConfig(quotas={"*": 2}),
                          extra_pods=("w2",))
    gw = stack.gateway
    status, body = add(gw, "workload", 2)
    assert status == 200 and body["result"] == "SUCCESS"
    assert body["tenant"] == "default"          # namespace is the tenant
    # same tenant (namespace default), third chip: over the cap
    status, body = add(gw, "w2", 1)
    assert status == 429 and body["result"] == "QuotaExceeded"
    assert body["tenant"] == "default"
    assert body["retry_after_s"] >= 0.1
    assert REGISTRY.admission_decisions.value(
        tenant="default", outcome="over_quota") >= 1
    # an EXPLICIT different tenant has its own (also *:2) budget
    status, body = add(gw, "w2", 1, tenant="teamB")
    assert status == 200, body
    assert body["tenant"] == "teamB"


def test_concurrent_same_tenant_attaches_cannot_stampede_quota():
    """Two same-tenant requests racing through admission must not BOTH
    slip under the cap: the admitted chips are reserved in-flight until
    the attempt resolves, so exactly one wins."""
    from gpumounter_tpu.utils.errors import QuotaExceededError
    broker = AttachBroker(FakeKubeClient(), BrokerConfig(quotas={"T": 2}))
    broker.ensure_rederived()
    results = []
    guard = threading.Lock()

    def slow_attempt():
        time.sleep(0.2)           # hold the in-flight window open
        return 200, {"result": "SUCCESS", "device_ids": ["a", "b"]}

    def run(pod):
        try:
            status, _ = broker.attach(
                tenant="T", priority="normal", namespace="d", pod=pod,
                chips=2, node="n", rid=pod, attempt_fn=slow_attempt)
        except QuotaExceededError:
            status = 429
        with guard:
            results.append(status)

    threads = [threading.Thread(target=run, args=(f"p{i}",))
               for i in range(2)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=10)
    assert sorted(results) == [200, 429], results


def test_detach_refunds_the_tenant(stack_factory):
    stack = stack_factory(config=BrokerConfig(quotas={"*": 2}))
    gw = stack.gateway
    assert add(gw, "workload", 2)[0] == 200
    assert add(gw, "workload", 1)[0] == 429
    assert remove(gw, "workload")[0] == 200
    assert gw.broker.leases.tenant_usage("default") == 0
    assert add(gw, "workload", 2)[0] == 200


def test_quota_burst_allows_borrowing_up_to_cap(stack_factory):
    stack = stack_factory(
        config=BrokerConfig(quotas={"hog": 2}, quota_burst=2.0))
    gw = stack.gateway
    # quota 2, burst 2 => cap 4: the whole node is borrowable while idle
    status, body = add(gw, "workload", 4, entire=True, tenant="hog")
    assert status == 200, body
    # ...but the cap is hard: one more chip is denied
    assert add(gw, "workload", 1, tenant="hog")[0] == 429


def test_http_surface_retry_after_header_and_allow(stack_factory):
    """Through a real HTTP server: 429 carries Retry-After, 405 carries
    Allow (the serve() header lift for both broker and method hygiene)."""
    stack = stack_factory(config=BrokerConfig(quotas={"*": 0}))
    server = stack.gateway.serve(port=0, address="127.0.0.1")
    base = f"http://127.0.0.1:{server.server_port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"{base}/addtpu/namespace/default/pod/workload"
                "/tpu/1/isEntireMount/false")
        assert err.value.code == 429
        assert int(err.value.headers["Retry-After"]) >= 1
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/addtpu/namespace/default/pod/workload"
                "/tpu/1/isEntireMount/false", data=b"", method="POST"))
        assert err.value.code == 405
        assert err.value.headers["Allow"] == "GET"
    finally:
        stack.gateway.fleet.stop()       # serve() started it too
        stack.gateway.broker.stop()
        server.shutdown()


def test_tenant_resolution_precedence_and_validation(stack_factory):
    stack = stack_factory(config=BrokerConfig(quotas={"teamQ": 0}))
    gw = stack.gateway
    # header names the tenant
    status, body = gw.handle(
        "GET", "/addtpu/namespace/default/pod/workload/tpu/1"
               "/isEntireMount/false",
        headers={"X-Tpu-Tenant": "teamQ"})
    assert status == 429 and body["tenant"] == "teamQ"
    # query param beats the header
    status, body = gw.handle(
        "GET", "/addtpu/namespace/default/pod/workload/tpu/1"
               "/isEntireMount/false?tenant=teamFree",
        headers={"X-Tpu-Tenant": "teamQ"})
    assert status == 200, body
    assert body["tenant"] == "teamFree"
    remove(gw, "workload")
    # garbage tenant / priority are 400s, not silent defaults
    status, body = add(gw, "workload", 1, tenant="bad/slash")
    assert status == 400 and body["result"] == "BadRequest"
    status, body = add(gw, "workload", 1, priority="urgent")
    assert status == 400 and body["result"] == "BadRequest"


# -- scheduling: queue + fairness + preemption ---------------------------------

def _wait_until(predicate, timeout_s=10.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def test_contended_attach_queues_then_completes(stack_factory):
    stack = stack_factory(
        config=BrokerConfig(queue_timeout_s=20.0), extra_pods=("w2",))
    gw = stack.gateway
    assert add(gw, "workload", 4, entire=True)[0] == 200
    done = {}

    def queued_attach():
        done["res"] = add(gw, "w2", 2)

    thread = threading.Thread(target=queued_attach)
    thread.start()
    _wait_until(lambda: len(gw.broker._waiters) == 1, what="enqueue")
    assert REGISTRY.queue_depth.value(priority="normal") == 1
    assert remove(gw, "workload")[0] == 200       # frees all 4 chips
    thread.join(timeout=20)
    assert not thread.is_alive()
    status, body = done["res"]
    assert status == 200 and body["result"] == "SUCCESS"
    assert body["queued_s"] >= 0.0
    assert REGISTRY.queue_wait.count(tenant="default") >= 1
    assert REGISTRY.admission_decisions.value(
        tenant="default", outcome="granted_queued") >= 1
    assert_broker_invariants(gw.broker, stack.rig.sim)


def test_queue_timeout_returns_insufficient_with_wait(stack_factory):
    stack = stack_factory(
        config=BrokerConfig(queue_timeout_s=0.2), extra_pods=("w2",))
    gw = stack.gateway
    assert add(gw, "workload", 4, entire=True)[0] == 200
    t0 = time.monotonic()
    status, body = add(gw, "w2", 2)
    assert time.monotonic() - t0 >= 0.2
    assert status == 503 and body["result"] == "INSUFFICIENT_TPU"
    assert body["queue_timeout"] is True and body["queued_s"] >= 0.19
    assert REGISTRY.admission_decisions.value(
        tenant="default", outcome="queue_timeout") >= 1
    assert gw.broker._waiters == []


def test_queue_full_sheds_with_429(stack_factory):
    stack = stack_factory(
        config=BrokerConfig(queue_timeout_s=20.0, queue_depth=1),
        extra_pods=("w2", "w3"))
    gw = stack.gateway
    assert add(gw, "workload", 4, entire=True)[0] == 200
    done = {}
    thread = threading.Thread(
        target=lambda: done.update(res=add(gw, "w2", 2)))
    thread.start()
    _wait_until(lambda: len(gw.broker._waiters) == 1, what="enqueue")
    status, body = add(gw, "w3", 2)               # the FIFO is at bound
    assert status == 429 and body["result"] == "QueueFull"
    assert body["retry_after_s"] > 0
    assert remove(gw, "workload")[0] == 200
    thread.join(timeout=20)
    assert done["res"][0] == 200


def test_dequeue_order_priority_then_weighted_fair():
    """Pure-broker determinism: released capacity is granted high-first,
    then across tenants by smallest quota-share in use, then FIFO."""
    broker = AttachBroker(FakeKubeClient(),
                          BrokerConfig(quotas={"A": 4, "B": 4},
                                       queue_timeout_s=30.0))
    broker.ensure_rederived()          # empty cluster: nothing derived
    # tenant A already holds 2 chips => B is fairness-first among normals
    broker.leases.record("default", "pre", "A", "normal", ["p0", "p1"])
    capacity = {"free": 0}
    guard = threading.Lock()
    order: list[str] = []

    def make_attempt(name: str):
        def attempt():
            with guard:
                if capacity["free"] >= 1:
                    capacity["free"] -= 1
                    order.append(name)
                    return 200, {"result": "SUCCESS",
                                 "device_ids": [f"{name}-0"]}
            return 503, {"result": "INSUFFICIENT_TPU"}
        return attempt

    waiters = (("low-a", "A", "low"), ("norm-a", "A", "normal"),
               ("norm-b", "B", "normal"), ("high-b", "B", "high"))
    threads = []
    for name, tenant, priority in waiters:
        threads.append(threading.Thread(
            target=lambda n=name, t=tenant, p=priority: broker.attach(
                tenant=t, priority=p, namespace="default", pod=n,
                chips=1, node="node-a", rid=n,
                attempt_fn=make_attempt(n))))
    for thread in threads:
        thread.start()
    _wait_until(lambda: len(broker._waiters) == 4, what="4 waiters parked")

    def settled():
        # the previous generation's baton chain has fully died down:
        # nobody is armed and everybody has retried the current gen —
        # without this, a freed chip can race a mid-chain retry and the
        # order reflects the race, not the dequeue policy
        with broker._lock:
            return all(w.tried_gen >= broker._gen
                       and not w.event.is_set()
                       for w in broker._waiters)

    for expected_len in range(1, 5):
        _wait_until(settled, what="baton chain settled")
        with guard:
            capacity["free"] += 1
        broker.signal_capacity()
        _wait_until(lambda: len(order) >= expected_len,
                    what=f"grant #{expected_len}")
    for thread in threads:
        thread.join(timeout=10)
    assert order == ["high-b", "norm-b", "norm-a", "low-a"], order


def test_high_priority_preempts_over_quota_victim(stack_factory):
    """The acceptance scenario: hog borrows the whole node via burst, a
    high-priority request of another tenant arrives, the broker preempts
    the hog's (lowest-priority, over-quota) attachment through the
    normal worker path — victim cleanly detached, cause visible in the
    audit event AND the node-local journal, chips re-granted."""
    stack = stack_factory(
        config=BrokerConfig(quotas={"hog": 2, "*": 4}, quota_burst=2.0,
                            queue_timeout_s=20.0),
        extra_pods=("hog-pod", "vip-pod"))
    gw = stack.gateway
    preempts_before = REGISTRY.preemptions.value()
    status, body = add(gw, "hog-pod", 4, entire=True, tenant="hog")
    assert status == 200, body
    status, body = add(gw, "vip-pod", 4, entire=True, tenant="vip",
                       priority="high", rid="vip-rid")
    assert status == 200, body
    assert body["result"] == "SUCCESS" and len(body["device_ids"]) == 4
    assert REGISTRY.preemptions.value() - preempts_before == 1
    # victim is fully gone: lease dropped, only vip's slave pods remain
    assert gw.broker.leases.get("default", "hog-pod") is None
    lease = gw.broker.leases.get("default", "vip-pod")
    assert lease is not None and lease.chips == 4
    wait_events_drained(stack.rig.service)
    causes = [e["message"] for e in stack.kube.events
              if e.get("reason") == "TPUDetached"]
    assert any("cause=preempted:vip:vip-rid" in m for m in causes), causes
    # journaled on the node: the detach record says who took the chips
    detach_records = [r for r in stack.rig.journal.snapshot()["records"]
                      if r["state"] == "detached"]
    assert any(r.get("cause", "").startswith("preempted:vip")
               for r in detach_records), detach_records
    assert_broker_invariants(gw.broker, stack.rig.sim)


def test_no_preemption_without_over_quota_victims(stack_factory):
    """Hard caps (burst 1.0) leave nothing preemptible: a high request
    waits out the queue like anyone else."""
    stack = stack_factory(
        config=BrokerConfig(quotas={"*": 4}, queue_timeout_s=0.2),
        extra_pods=("w2",))
    gw = stack.gateway
    assert add(gw, "workload", 4, entire=True)[0] == 200
    preempts_before = REGISTRY.preemptions.value()
    status, body = add(gw, "w2", 2, tenant="other", priority="high")
    assert status == 503 and body.get("queue_timeout")
    assert REGISTRY.preemptions.value() == preempts_before
    assert gw.broker.leases.get("default", "workload").chips == 4


# -- leases: expiry, renewal ---------------------------------------------------

def test_expired_lease_auto_detaches_and_frees_chips(stack_factory):
    stack = stack_factory(config=BrokerConfig(lease_ttl_s=0.3))
    gw = stack.gateway
    expirations_before = REGISTRY.lease_expirations.value()
    status, body = add(gw, "workload", 4, entire=True, rid="short-lease")
    assert status == 200
    assert 0 < body["lease_expires_in_s"] <= 0.4
    assert gw.broker.tick() == 0          # not expired yet
    time.sleep(0.35)
    assert gw.broker.tick() == 1          # reaped exactly one
    assert gw.broker.leases.leases() == []
    assert stack.rig.sim.slave_pods() == []   # chips drained back
    assert REGISTRY.lease_expirations.value() - expirations_before == 1
    wait_events_drained(stack.rig.service)
    causes = [e["message"] for e in stack.kube.events
              if e.get("reason") == "TPUDetached"]
    assert any("cause=lease-expired:short-lease" in m for m in causes)
    # the node is reusable immediately
    assert add(gw, "workload", 4, entire=True)[0] == 200
    assert_broker_invariants(gw.broker, stack.rig.sim)


def test_renew_extends_the_lease(stack_factory):
    stack = stack_factory(config=BrokerConfig(lease_ttl_s=0.3))
    gw = stack.gateway
    assert add(gw, "workload", 2)[0] == 200
    status, body = gw.handle(
        "POST", "/renew/namespace/default/pod/workload?ttl=60")
    assert status == 200 and body["result"] == "SUCCESS"
    assert body["lease"]["expires_in_s"] > 50
    assert body["lease"]["renewals"] == 1
    time.sleep(0.35)
    assert gw.broker.tick() == 0          # renewed: outlives the old TTL
    assert len(stack.rig.sim.slave_pods()) == 2
    # an unknown lease cannot be renewed (expired-and-reaped contract)
    status, body = gw.handle("POST", "/renew/namespace/default/pod/ghost")
    assert status == 404 and body["result"] == "LeaseNotFound"
    # wrong method on a known route: 405 + Allow, not 404
    status, body = gw.handle("GET",
                             "/renew/namespace/default/pod/workload")
    assert status == 405 and body["allow"] == "POST"


def test_expiry_reap_defers_on_busy_devices(stack_factory):
    """A lease whose devices are held open is NOT force-killed: the reap
    defers with backoff and the lease stays visible as stuck."""
    stack = stack_factory(config=BrokerConfig(lease_ttl_s=0.3))
    gw = stack.gateway
    status, body = add(gw, "workload", 1)
    assert status == 200
    path = body["device_paths"][0]
    stack.rig.sim.enumerator.busy_pids = {path: [stack.rig.pid]}
    time.sleep(0.35)
    assert gw.broker.tick() == 0                    # deferred, not reaped
    lease = gw.broker.leases.get("default", "workload")
    assert lease is not None and lease.reap_failures == 1
    assert len(stack.rig.sim.slave_pods()) == 1     # chips still granted
    stack.rig.sim.enumerator.busy_pids = {}
    time.sleep(2.1)                                 # past the backoff
    assert gw.broker.tick() == 1
    assert stack.rig.sim.slave_pods() == []


# -- restart re-derivation -----------------------------------------------------

def test_master_restart_rederives_quotas_from_ground_truth(stack_factory):
    stack = stack_factory(config=BrokerConfig(quotas={"*": 4}),
                          extra_pods=("w2",))
    assert add(stack.gateway, "workload", 4, entire=True,
               rid="original")[0] == 200
    # "restart": a brand-new gateway + broker over the same cluster
    gw2 = stack.new_gateway(BrokerConfig(quotas={"*": 4}))
    status, body = gw2.handle("GET", "/brokerz")
    assert status == 200
    assert body["leases"]["count"] == 1
    (lease,) = body["leases"]["leases"]
    assert lease["pod"] == "workload" and lease["chips"] == 4
    assert lease["tenant"] == "default"        # collapses to namespace
    assert lease["rederived"] is True
    assert lease["rid"] == "original"          # from the request-id label
    # quota enforcement continues seamlessly across the restart
    status, body = add(gw2, "w2", 1)
    assert status == 429 and body["result"] == "QuotaExceeded"
    # zero double-actuation: a tick on the fresh broker detaches nothing
    detaches_before = REGISTRY.detach_results.value(result="SUCCESS")
    assert gw2.broker.tick() == 0
    assert REGISTRY.detach_results.value(
        result="SUCCESS") == detaches_before
    assert len(stack.rig.sim.slave_pods()) == 1
    # the re-derived lease is live: detach through the NEW master works
    assert remove(gw2, "workload")[0] == 200
    assert gw2.broker.leases.tenant_usage("default") == 0
    assert add(gw2, "w2", 1)[0] == 200
    assert_broker_invariants(gw2.broker, stack.rig.sim)


def test_rederived_lease_gets_fresh_ttl_then_expires_once(stack_factory):
    stack = stack_factory(config=BrokerConfig(lease_ttl_s=30.0))
    assert add(stack.gateway, "workload", 2)[0] == 200
    gw2 = stack.new_gateway(BrokerConfig(lease_ttl_s=0.3))
    assert gw2.broker.tick() == 0            # fresh TTL: no insta-reap
    assert len(stack.rig.sim.slave_pods()) == 2
    time.sleep(0.35)
    assert gw2.broker.tick() == 1            # then exactly one expiry
    assert stack.rig.sim.slave_pods() == []
    wait_events_drained(stack.rig.service)
    detached = [e for e in stack.kube.events
                if e.get("reason") == "TPUDetached"]
    assert len(detached) == 1                # no double-detach


# -- gateway method hygiene (satellite) ----------------------------------------

def test_known_routes_wrong_method_405_with_allow(stack_factory):
    gw = stack_factory().gateway
    for method, path, allow in (
            ("POST", "/healthz", "GET"),
            ("POST", "/version", "GET"),
            ("POST", "/addtpu/namespace/d/pod/p/tpu/1"
                     "/isEntireMount/true", "GET"),
            ("GET", "/removetpu/namespace/d/pod/p/force/false", "POST"),
            ("POST", "/tpustatus/namespace/d/pod/p", "GET"),
            ("POST", "/nodestatus/node/n", "GET"),
            ("GET", "/addtpuslice", "POST"),
            ("GET", "/removetpuslice", "POST"),
            ("POST", "/tracez", "GET"),
            ("POST", "/brokerz", "GET")):
        status, body = gw.handle(method, path)
        assert status == 405, (method, path, status)
        assert body["result"] == "MethodNotAllowed"
        assert body["allow"] == allow
    # unknown paths still 404
    status, body = gw.handle("GET", "/nope")
    assert status == 404 and body["result"] == "NoSuchRoute"


def test_version_route_unchanged(stack_factory):
    import gpumounter_tpu
    gw = stack_factory().gateway
    status, body = gw.handle("GET", "/version")
    assert status == 200 and body["version"] == gpumounter_tpu.__version__


# -- slice admission -----------------------------------------------------------

def test_slice_attach_is_quota_gated(stack_factory):
    stack = stack_factory(config=BrokerConfig(quotas={"*": 2}))
    body = json.dumps({"pods": [{"namespace": "default",
                                 "pod": "workload"}],
                       "tpusPerHost": 4}).encode()
    status, payload = stack.gateway.handle("POST", "/addtpuslice", body)
    assert status == 429 and payload["result"] == "QuotaExceeded"
    # under quota, the slice attaches and records a lease
    body = json.dumps({"pods": [{"namespace": "default",
                                 "pod": "workload"}],
                       "tpusPerHost": 2, "tenant": "sliceTeam"}).encode()
    status, payload = stack.gateway.handle("POST", "/addtpuslice", body)
    assert status == 200, payload
    assert stack.gateway.broker.leases.tenant_usage("sliceTeam") == 2


def test_queue_hints_derive_from_waiters_and_lease_horizon(stack_factory):
    """ISSUE 8 satellite: queue-full and queue-timeout shed responses
    carry a DERIVED Retry-After — queue-full from the oldest
    same-priority waiter's remaining deadline (a slot frees no later
    than that), queue-timeout from the lease horizon (when chips can
    actually expire free) — not the old blind 1-second constant."""
    stack = stack_factory(
        config=BrokerConfig(queue_timeout_s=20.0, queue_depth=1,
                            lease_ttl_s=45.0),
        extra_pods=("w2", "w3"))
    gw = stack.gateway
    assert add(gw, "workload", 4, entire=True)[0] == 200
    done = {}
    thread = threading.Thread(
        target=lambda: done.update(res=add(gw, "w2", 2)))
    thread.start()
    _wait_until(lambda: len(gw.broker._waiters) == 1, what="enqueue")
    status, body = add(gw, "w3", 2)               # FIFO at bound: shed
    assert status == 429 and body["result"] == "QueueFull"
    # the parked waiter dies in <= 20s, so the hint must say ~that —
    # not 1s (hammering a full node) and never past the deadline
    assert 10.0 <= body["retry_after_s"] <= 20.0
    assert remove(gw, "workload")[0] == 200
    thread.join(timeout=30)
    assert done["res"][0] == 200
    assert remove(gw, "w2")[0] == 200

    # queue-timeout: the ONLY capacity signal is the 45s lease TTL on a
    # fresh hold — the timed-out waiter's hint is the lease horizon
    # (clamped to 60), not a constant
    assert add(gw, "workload", 4, entire=True)[0] == 200
    broker = gw.broker
    status, body = broker.attach(
        tenant="default", priority="normal", namespace="default",
        pod="w3", chips=2, node="node-a", rid="hint-1",
        attempt_fn=lambda: (503, {"result": "INSUFFICIENT_TPU"}),
        timeout_s=0.2)
    assert status == 503 and body["queue_timeout"]
    assert 30.0 <= body["retry_after_s"] <= 45.0, body
    stack.close()
