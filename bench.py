"""Benchmark: hot-attach latency through the full control plane, plus a
real-chip JAX metric when TPU hardware is present.

Control-plane measurement drives the complete AddTPU/RemoveTPU path — HTTP
master gateway → gRPC worker → allocator (slave pods through a scripted
scheduler) → real cgroup-v1 device-permission writes + device-node actuation
on a fixture host tree, with the collector reading a real gRPC unix-socket
kubelet. Two configurations are measured:

- **overhead**: scheduler delay 0 — the framework's own cost per attach
  (every socket/file real, the cluster instantaneous);
- **e2e**: a 1.0 s injected scheduler+device-plugin delay per slave pod —
  the realistic dominant cost the reference pays unthrottled-polling for
  (``allocator.go:237-283``); our watch-based allocator should add only
  the overhead number on top of it;
- **e2e-with-pool**: the same injected delay, but a warm slave-pod pool
  (worker/pool.py) absorbs it off the request path — each timed attach
  adopts a pre-scheduled warm pod, so the pool-hit p50 should land next
  to the bare overhead, not next to the cold e2e number. This config also
  counts **apiserver round-trips per attach** (by verb, from the
  ``k8s_request_seconds`` family the in-process worker shares): with the
  shared informer wired the warm path performs ZERO LISTs.
- **multi-chip**: an 8-chip entire-node attach (overhead mode) — the
  fused-actuation configuration, where all mknods for a container ride
  ONE namespace crossing (``multi_chip_attach_p50_s``).
- **contention**: two tenants firing more concurrent attaches than the
  node holds through the master's attach broker (quota admission +
  priority queue), plus a preemption scenario — emits
  ``queued_attach_wait_p50_s`` and ``preemption_e2e_p50_s``.

Every rig runs with the shared pod informer enabled — the production
default wiring (worker/main.py).

The headline metric is the **e2e p50** (honest, delay included); p99 and
the bare overhead are reported alongside. The reference publishes no
numbers (BASELINE.md) — the target is the BASELINE.json north star: < 3 s
p50 for a 4-chip entire-mount.

When a real TPU backend initialises (the bench host's chip), the JAX
selftest (:mod:`gpumounter_tpu.jaxcheck.tpu_selftest`) runs in a subprocess
and its hardware evidence — train-step ms on the chip, pallas-vs-oracle
parity error, backend re-init time — is embedded under ``"tpu"``.

Output contract: the FULL result (with the complete TPU report) goes to
stderr and ``BENCH_DETAIL.json``; stdout's final line is a COMPACT
single-line JSON summary — the harness parses the last stdout line, and a
multi-KB line gets truncated by its tail window (every BENCH_r0*.json
with an embedded selftest parsed as null before this split).
"""

from __future__ import annotations

import http.client
import json
import math
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_P50_S = 3.0
CHIPS = 4
SCHED_DELAY_S = 1.0     # injected scheduler+kubelet cost for the e2e config

# Sustained-RPS gateway config: concurrent single-chip attach clients
# driven through the full master→worker stack at once. 550 > the 500
# concurrent-in-flight acceptance bar so the peak-inflight reading has
# margin over scheduling jitter.
SUSTAINED_CLIENTS = 550

# The 10k-admission-path config (ISSUE 14): the SAME workload at 2,000
# concurrent in-flight clients over the parking-executor worker
# (TPU_GRPC_ASYNC semantics: grpc_workers bounds ACTIVE threads, slow
# waits park) and a wider gateway front. The 550-client config above is
# kept byte-identical for trajectory comparability.
SUSTAINED_2K_CLIENTS = 2000

# Multi-master config (measure_multimaster): modeled apiserver write RTT
# for one state-ConfigMap CAS — the per-shard serialized resource the
# hash ring partitions. ~an etcd-backed PATCH on a loaded apiserver.
MM_STORE_WRITE_RTT_S = 0.075


def _bench_root(prefix: str) -> str:
    """Fixture tree root. Prefer tmpfs: the real /dev is devtmpfs and the
    real cgroupfs is an in-RAM virtual fs, so RAM-backed fixture syscalls
    model production cost; a 9p/overlay /tmp overstates every mknod/stat
    by an order of magnitude and would benchmark the harness filesystem,
    not the framework."""
    base = "/dev/shm" if os.access("/dev/shm", os.W_OK) else None
    return tempfile.mkdtemp(prefix=prefix, dir=base)


class _Client:
    """Keep-alive HTTP client for one master: the gateway front speaks
    HTTP/1.1, and a sustained attach/detach driver reuses its connection
    like any real client (a fresh TCP handshake per request would
    benchmark connection setup, which the multiplexed front exists to
    amortise)."""

    def __init__(self, base: str):
        host, _, port = base.rpartition("//")[2].rpartition(":")
        self.conn = http.client.HTTPConnection(host, int(port), timeout=180)

    def request(self, method: str, path: str, body: bytes | None = None,
                headers: dict | None = None) -> dict:
        try:
            self.conn.request(method, path, body=body,
                              headers=headers or {})
        except (http.client.HTTPException, OSError):
            # SEND-side failure (stale keep-alive socket): the request
            # never reached the server, so a reconnect + resend is safe
            # even for non-idempotent verbs
            self.conn.close()
            self.conn.request(method, path, body=body,
                              headers=headers or {})
        try:
            resp = self.conn.getresponse()
        except http.client.RemoteDisconnected:
            # the server closed the connection WITHOUT sending any
            # response — the idle-keep-alive race (it reaped the conn as
            # our request was in flight, before reading it). Any failure
            # mode where the request might have been processed raises a
            # different error and propagates: blindly retrying a
            # processed attach would double-attach.
            self.conn.close()
            self.conn.request(method, path, body=body,
                              headers=headers or {})
            resp = self.conn.getresponse()
        return json.loads(resp.read())

    def close(self) -> None:
        self.conn.close()


def _k8s_counts() -> dict:
    """(verb, resource) -> cumulative round-trip count, from the shared
    in-process registry (the LiveStack worker runs in-process, so its
    instrumentation IS this process's)."""
    from gpumounter_tpu.utils.metrics import REGISTRY
    return {(d["verb"], d["resource"]): REGISTRY.k8s_latency.count(**d)
            for d in REGISTRY.k8s_latency.phases()}


def measure_attach_cycle(schedule_delay_s: float, cycles: int,
                         n_chips: int = CHIPS, entire: bool = True,
                         warm_pool: bool = False,
                         count_round_trips: bool = False,
                         usage: bool = True,
                         topo: bool = True,
                         grpc_mode: str = "threadpool"
                         ) -> tuple[list[float], list[float], list[dict]]:
    """Drive attach+detach cycles; returns (attach_latencies,
    detach_latencies, per_attach_round_trips) in seconds / verb-counts.

    ``warm_pool=True`` sizes a warm slave-pod pool to exactly cover one
    attach and refills it between cycles OFF the timed path — each timed
    attach is then a pure pool hit, which is the number the pool exists to
    produce: the injected scheduler delay is paid by the refill loop, not
    the attach.

    ``count_round_trips=True`` snapshots the apiserver call counters
    around each TIMED attach and records the per-verb deltas for pods/
    nodes (events are async audit noise, kubelet is a different hop)."""
    from gpumounter_tpu.testing.sim import LiveStack, WorkerRig
    from gpumounter_tpu.utils.config import HostPaths

    root = _bench_root("tpumounter-bench-")
    host = HostPaths(dev_root=f"{root}/dev", proc_root=f"{root}/proc",
                     sys_root=f"{root}/sys",
                     cgroup_root=f"{root}/sys/fs/cgroup",
                     kubelet_socket=f"{root}/pr/kubelet.sock")
    for d in (host.dev_root, host.proc_root, host.cgroup_root):
        os.makedirs(d)

    pool_sizes = None
    if warm_pool:
        pool_sizes = ({f"entire:{n_chips}": 1} if entire
                      else {"single:1": n_chips})
    # usage=True is the production default wiring: the chip usage
    # sampler (collector/usage.py, FsUsageProbe over the fixture tree)
    # runs its own thread at a tight interval CONCURRENTLY with the
    # timed attaches — the headline overhead number includes it, and the
    # usage=False re-measure is the TPU_USAGE=0 A/B
    # (utilz_overhead_delta_ms).
    # topo=True is likewise the production default: the worker serves
    # /topoz and the master's fleet tick scrapes+scores it concurrently
    # with the timed attaches; topo=False is the TPU_TOPOLOGY=0 A/B
    # (topoz_scrape_delta_ms).
    rig = WorkerRig(host, n_chips=max(CHIPS, n_chips), actuator="procroot",
                    use_kubelet_socket=True,
                    schedule_delay_s=schedule_delay_s,
                    warm_pool=pool_sizes, informer=True, agent=True,
                    usage="fs" if usage else False,
                    usage_interval_s=0.2, topo=topo)
    if rig.usage is not None:
        rig.usage.start()
    # the gateway reads TPU_TOPOLOGY at construction; pin it for the
    # stack build so the A/B actually removes the scrape + scoring
    prev_topology = os.environ.get("TPU_TOPOLOGY")
    if not topo:
        os.environ["TPU_TOPOLOGY"] = "0"
    try:
        stack = LiveStack(rig, grpc_mode=grpc_mode)
    finally:
        if not topo:
            if prev_topology is None:
                os.environ.pop("TPU_TOPOLOGY", None)
            else:
                os.environ["TPU_TOPOLOGY"] = prev_topology
    client = _Client(stack.base)
    attach = (f"/addtpu/namespace/default/pod/workload"
              f"/tpu/{n_chips}/isEntireMount/{str(entire).lower()}")
    detach = "/removetpu/namespace/default/pod/workload/force/false"
    try:
        if warm_pool:
            rig.fill_warm_pool()
        attach_lat, detach_lat, round_trips = [], [], []
        for _ in range(cycles):
            before = _k8s_counts() if count_round_trips else None
            t0 = time.monotonic()
            body = client.request("GET", attach)
            attach_lat.append(time.monotonic() - t0)
            if before is not None:
                after = _k8s_counts()
                round_trips.append({
                    f"{verb}/{res}": after[(verb, res)]
                    - before.get((verb, res), 0)
                    for verb, res in after
                    if res in ("pods", "nodes")
                    and after[(verb, res)] != before.get((verb, res), 0)})
            assert body["result"] == "SUCCESS", body
            payload = json.dumps({"uuids": body["device_ids"]}).encode()
            t0 = time.monotonic()
            assert client.request("POST", detach,
                                  body=payload)["result"] == "SUCCESS"
            detach_lat.append(time.monotonic() - t0)
            if warm_pool:
                rig.fill_warm_pool()        # refill off the timed path
        return attach_lat, detach_lat, round_trips
    finally:
        client.close()
        stack.close()
        shutil.rmtree(root, ignore_errors=True)


def measure_contention(cycles: int = 3) -> dict:
    """Broker contention benchmark: two tenants firing more concurrent
    attaches than the node has chips, through the master's admission
    queue, plus a preemption scenario (an over-quota tenant's borrowed
    chips reclaimed for a high-priority request).

    Emits ``queued_attach_wait_p50_s`` — the REAL wakeup latency: per
    queued winner, the ``queued_s`` its own response reports (enqueue →
    woken → retried → success). The previous config derived this from
    the process-global queue-wait histogram and released capacity only
    after a racy winners-scan of client-side state; when that scan lost
    the race (loaded machine), the parked pair sat out the entire
    ``TPU_QUEUE_TIMEOUT_S`` and the metric reported the TIMEOUT constant
    (60.0006 s in BENCH_r05) instead of wakeup latency. Now capacity
    release keys off the broker's own lease table (``/brokerz``), every
    contender is asserted to finish SUCCESS with no ``queue_timeout``,
    and the selftest asserts the p50 is far below the timeout."""
    from gpumounter_tpu.master.admission import BrokerConfig
    from gpumounter_tpu.testing.sim import LiveStack, WorkerRig
    from gpumounter_tpu.utils.config import HostPaths
    from gpumounter_tpu.utils.metrics import REGISTRY

    root = _bench_root("tpumounter-bench-broker-")
    host = HostPaths(dev_root=f"{root}/dev", proc_root=f"{root}/proc",
                     sys_root=f"{root}/sys",
                     cgroup_root=f"{root}/sys/fs/cgroup",
                     kubelet_socket=f"{root}/pr/kubelet.sock")
    for d in (host.dev_root, host.proc_root, host.cgroup_root):
        os.makedirs(d)
    rig = WorkerRig(host, n_chips=CHIPS, actuator="procroot",
                    use_kubelet_socket=True, informer=True, agent=True)
    # hog's quota is half the node but burst 2 lets it borrow the rest —
    # the borrowed half is exactly what the high-priority vip preempts.
    queue_timeout_s = 60.0
    config = BrokerConfig(
        quotas={"teamA": CHIPS, "teamB": CHIPS, "hog": CHIPS // 2},
        quota_burst=2.0, queue_timeout_s=queue_timeout_s)
    stack = LiveStack(rig, broker_config=config, shared_kube=True)
    contenders = ("w-a1", "w-a2", "w-b1", "w-b2")

    def add_pod(name: str) -> None:
        pod = rig.sim.add_target_pod(name=name)
        rig.provision_container(pod)

    def attach(client: _Client, pod: str, n: int, tenant: str,
               priority: str = "normal") -> tuple[float, dict]:
        path = (f"/addtpu/namespace/default/pod/{pod}"
                f"/tpu/{n}/isEntireMount/true"
                f"?tenant={tenant}&priority={priority}")
        t0 = time.monotonic()
        body = client.request("GET", path)
        return time.monotonic() - t0, body

    def detach(client: _Client, pod: str) -> None:
        client.request("POST",
                       f"/removetpu/namespace/default/pod/{pod}"
                       "/force/false", body=b"")

    def broker_holders(client: _Client) -> tuple[set[str], int]:
        """(contender pods holding a live lease, queued waiter count) —
        the broker's OWN view, immune to client-side response races."""
        brokerz = client.request("GET", "/brokerz")
        held = {lease["pod"]
                for lease in brokerz.get("leases", {}).get("leases", [])
                if lease["pod"] in contenders}
        return held, sum(brokerz["queue"]["depth"].values())

    for name in (*contenders, "hog", "vip"):
        add_pod(name)
    half = CHIPS // 2
    control = _Client(stack.base)
    queued_waits: list[float] = []
    # indexed-wakeup accounting (ISSUE 14): candidates examined per
    # capacity signal over the whole contention run — with the index
    # this tracks per-node candidates, not total parked waiters
    ev0 = REGISTRY.wakeup_evaluations.value()
    sig0 = REGISTRY.wakeup_signals.value()
    try:
        # -- queued contention: 4 x half-node over one node, two tenants
        for _ in range(cycles):
            results: dict[str, dict] = {}
            clients = {pod: _Client(stack.base) for pod in contenders}

            def run(pod: str, tenant: str) -> None:
                results[pod] = attach(clients[pod], pod, half, tenant)[1]

            threads = [threading.Thread(target=run, args=pair)
                       for pair in (("w-a1", "teamA"), ("w-b1", "teamB"),
                                    ("w-a2", "teamA"), ("w-b2", "teamB"))]
            for th in threads:
                th.start()
            # Release capacity from the broker's OWN state: once its
            # lease table shows the two winners AND both losers are
            # parked, detach the winners — the parked pair's wakeup is
            # then guaranteed by the broker contract, not by this
            # driver winning a scan race.
            deadline = time.monotonic() + 30.0
            winners: set[str] = set()
            while time.monotonic() < deadline:
                held, depth = broker_holders(control)
                if len(held) >= 2 and depth >= 2:
                    winners = held
                    break
                time.sleep(0.02)
            assert winners, "contention cycle never reached 2 leases + " \
                            "2 parked waiters; broker state: " \
                            f"{control.request('GET', '/brokerz')}"
            for pod in winners:
                detach(control, pod)
            for th in threads:
                th.join(timeout=queue_timeout_s + 30)
            # bench selftest: every contender succeeded, nobody timed out
            # of the queue, and the queued pair reports real wakeup waits
            for pod in contenders:
                body = results.get(pod) or {}
                assert body.get("result") == "SUCCESS", (pod, body)
                assert not body.get("queue_timeout"), (pod, body)
                if "queued_s" in body:
                    queued_waits.append(float(body["queued_s"]))
                if pod not in winners:
                    detach(control, pod)
            for client in clients.values():
                client.close()
        assert queued_waits, "no attach was ever queued — the contention " \
                             "config measured nothing"
        queued_wait_p50 = statistics.median(queued_waits)
        # the whole point of the fix: the metric is wakeup latency, not
        # the queue-timeout constant
        assert queued_wait_p50 < queue_timeout_s / 2, (
            f"queued wait p50 {queued_wait_p50:.3f}s is in timeout "
            f"territory (timeout {queue_timeout_s}s): waiters are not "
            "being woken by freed capacity")

        # -- preemption: hog borrows the whole node, vip (high) reclaims
        preempt_lat = []
        for _ in range(cycles):
            _, body = attach(control, "hog", CHIPS, "hog")
            assert body["result"] == "SUCCESS", body
            elapsed, body = attach(control, "vip", CHIPS, "teamA",
                                   priority="high")
            assert body["result"] == "SUCCESS", body
            preempt_lat.append(elapsed)
            detach(control, "vip")
        signals = REGISTRY.wakeup_signals.value() - sig0
        evaluations = REGISTRY.wakeup_evaluations.value() - ev0
        return {
            "queued_attach_wait_p50_s": round(queued_wait_p50, 4),
            "queued_attach_samples": len(queued_waits),
            "preemption_e2e_p50_s": round(
                statistics.median(preempt_lat), 4),
            "preemptions": int(REGISTRY.preemptions.value()),
            "contention_cycles": cycles,
            "wakeup_evaluations_per_signal": round(
                evaluations / max(signals, 1), 2),
            "wakeup_signals": int(signals),
        }
    finally:
        control.close()
        stack.close()
        shutil.rmtree(root, ignore_errors=True)


def measure_multimaster(window_s: float = 5.0,
                        clients_per_tenant: int = 6,
                        scaling_retries: int = 1) -> dict:
    """Multi-master scale-out benchmark (ISSUE 8 acceptance): admission
    throughput of 2 leader-elected masters (one shard each) vs 1 master
    (one shard) on the same two-tenant contention workload, both with
    the full HA plane on (election + intent store).

    What is being scaled: with durable intent, every grant/release is a
    resourceVersion CAS against the shard's state ConfigMap — one
    optimistic-concurrency stream per shard, so same-shard writes
    serialize (a loser re-reads and re-patches) while different shards
    are independent. The fake cluster answers in microseconds, which
    would benchmark the GIL instead of the architecture, so a modeled
    apiserver write RTT (``MM_STORE_WRITE_RTT_S``, ~a real etcd-backed
    PATCH) is injected on state-ConfigMap writes only (election lock
    traffic stays instant). Sharding the keyspace is then worth exactly
    what the design claims: N masters = N independent CAS streams ≈ N×
    admission throughput. The workload: per tenant (= namespace, each
    hashing to its own shard), ``clients_per_tenant`` concurrent clients
    cycle 2-chip attach→detach against the tenant's shard leader for
    ``window_s``; reported is aggregate completed cycles/s and the
    2-vs-1 scaling ratio (the acceptance bar is >= 1.8x).

    The PR 7 single-replica baseline needs no separate config here: the
    overhead/e2e/contention configs above all run with the HA knobs at
    their defaults (off), so their p50s ARE the PR 7-semantics numbers."""
    from gpumounter_tpu.master.admission import BrokerConfig
    from gpumounter_tpu.master.shardring import ShardRing
    from gpumounter_tpu.testing.sim import MultiMasterStack, WorkerRig
    from gpumounter_tpu.utils import consts
    from gpumounter_tpu.utils.config import HostPaths

    # two tenant namespaces, one per shard of the 2-ring (stable sha256
    # hash, so the probe is deterministic across runs)
    ring = ShardRing(2)
    ns_by_shard: dict[int, str] = {}
    i = 0
    while len(ns_by_shard) < 2:
        ns_by_shard.setdefault(ring.shard_of(f"team-{i}"), f"team-{i}")
        i += 1
    tenants = [ns_by_shard[0], ns_by_shard[1]]

    def run_topology(masters: int, shards: int,
                     group_commit_s: float = 0.0) -> tuple[float, float]:
        """Returns (admission cycles/s, store CAS ops per admission).
        ``group_commit_s`` > 0 runs the coalescer (ISSUE 14): queued
        record mutations fuse into ONE CAS per shard, so the serialized
        per-shard write stream carries many admissions per round trip
        — the cas-per-admission figure is what the fusion buys."""
        root = _bench_root("tpumounter-bench-mm-")
        host = HostPaths(dev_root=f"{root}/dev", proc_root=f"{root}/proc",
                         sys_root=f"{root}/sys",
                         cgroup_root=f"{root}/sys/fs/cgroup",
                         kubelet_socket=f"{root}/pr/kubelet.sock")
        for d in (host.dev_root, host.proc_root, host.cgroup_root):
            os.makedirs(d)
        # enough chips that admission, not the node, is the contended
        # resource: every client's 2-chip attach must fit at once
        chips = 4 * len(tenants) * clients_per_tenant   # 2/attach + slack
        rig = WorkerRig(host, n_chips=chips, actuator="procroot",
                        use_kubelet_socket=True, informer=True, agent=True)
        stack = MultiMasterStack(
            rig, masters=masters, shards=shards,
            broker_config=BrokerConfig(), store=True, election=True,
            renew_interval_s=0.5, lease_duration_s=2.0,
            group_commit_s=group_commit_s)
        kube = rig.sim.kube
        # The modeled apiserver write RTT, state ConfigMaps only
        # (election lock traffic stays instant). Writes to one state
        # object are serialized under a per-object lock and committed
        # unconditionally: etcd serializes per-key writes server-side,
        # and in the steady state each shard map has ONE writer (its
        # leader), so modeling a master's own concurrent request
        # threads as a queue instead of optimistic-concurrency churn
        # keeps the measurement deterministic — the per-shard stream
        # commits exactly 1/RTT writes/s, which is the resource the
        # hash ring multiplies.
        real_patch = kube.patch_config_map
        real_create = kube.create_config_map
        import collections
        write_locks = collections.defaultdict(threading.Lock)

        def slow_patch(ns, name, patch, resource_version=None):
            if not name.startswith(consts.STORE_CONFIGMAP_PREFIX):
                return real_patch(ns, name, patch,
                                  resource_version=resource_version)
            with write_locks[name]:
                time.sleep(MM_STORE_WRITE_RTT_S)
                return real_patch(ns, name, patch, resource_version=None)

        def slow_create(ns, obj):
            name = obj.get("metadata", {}).get("name", "")
            if not name.startswith(consts.STORE_CONFIGMAP_PREFIX):
                return real_create(ns, obj)
            with write_locks[name]:
                time.sleep(MM_STORE_WRITE_RTT_S)
                return real_create(ns, obj)

        kube.patch_config_map = slow_patch
        kube.create_config_map = slow_create
        try:
            stack.wait_converged()
            base_for = {tenant: stack.bases[stack.leader_for(tenant)]
                        for tenant in tenants}
            counts: dict[str, int] = {}
            errors: list[str] = []
            stop = threading.Event()

            def cycle(tenant: str, idx: int) -> None:
                pod = f"mm-{tenant}-{idx}"
                rig.provision_container(
                    rig.sim.add_target_pod(name=pod, namespace=tenant))
                client = _Client(base_for[tenant])
                attach = (f"/addtpu/namespace/{tenant}/pod/{pod}"
                          f"/tpu/2/isEntireMount/false")
                detach = (f"/removetpu/namespace/{tenant}/pod/{pod}"
                          "/force/false")
                done = 0
                try:
                    # warmup cycle: creates the shard state map, primes
                    # caches, resolves the CM create race off the clock
                    client.request("GET", attach)
                    client.request("POST", detach, body=b"")
                    barrier.wait(timeout=60)
                    while not stop.is_set():
                        body = client.request("GET", attach)
                        if body.get("result") != "SUCCESS":
                            errors.append(f"{pod}: {body.get('result')}")
                            break
                        body = client.request("POST", detach, body=b"")
                        if body.get("result") != "SUCCESS":
                            errors.append(f"{pod}: {body.get('result')}")
                            break
                        done += 1
                finally:
                    counts[pod] = done
                    client.close()

            barrier = threading.Barrier(
                len(tenants) * clients_per_tenant + 1)
            threads = [threading.Thread(target=cycle, args=(tenant, idx))
                       for tenant in tenants
                       for idx in range(clients_per_tenant)]
            for th in threads:
                th.start()
            barrier.wait(timeout=60)      # all warmed up and lined up
            from gpumounter_tpu.utils.metrics import REGISTRY
            cas0 = sum(REGISTRY.store_cas.series().values())
            t0 = time.monotonic()
            time.sleep(window_s)
            stop.set()
            for th in threads:
                th.join(timeout=120)
            # clients check the flag between cycles, so the wall clock
            # runs to the LAST join — count it all, not just window_s
            elapsed = time.monotonic() - t0
            # settle the coalescer so its trailing flush is in the count
            for gateway in stack.gateways:
                if gateway.broker.store is not None:
                    gateway.broker.store.flush_pending()
            cas_ops = sum(REGISTRY.store_cas.series().values()) - cas0
            assert not errors, \
                f"multi-master cycles failed ({masters} master(s)): " \
                f"{errors[:5]}"
            total = sum(counts.values())
            assert total > 0, f"no cycles completed ({masters} master(s))"
            return total / elapsed, cas_ops / total
        finally:
            kube.patch_config_map = real_patch
            kube.create_config_map = real_create
            stack.close()
            shutil.rmtree(root, ignore_errors=True)

    single, single_cas = run_topology(masters=1, shards=1)
    dual, _ = run_topology(masters=2, shards=2)
    scaling = dual / single
    # bench selftest: the scale-out claim must hold, not just render —
    # 2 independent CAS streams must approach 2x one stream's admission
    # throughput (1.8x bar per the issue; a ratio near 1.0 means the
    # sharded stores are secretly serializing somewhere). The ratio is
    # suite-load-sensitive right at the bar (observed 1.79x under a
    # loaded box): before FAILING, re-measure BOTH topologies in the
    # same run on a doubled window — a genuine serialization bug
    # reproduces at any window; scheduler noise averages out. The bar
    # itself never moves.
    retries_used = 0
    while scaling < 1.8 and retries_used < scaling_retries:
        retries_used += 1
        window_s *= 2            # run_topology reads the closure var
        single, single_cas = run_topology(masters=1, shards=1)
        dual, _ = run_topology(masters=2, shards=2)
        scaling = dual / single
    assert scaling >= 1.8, (
        f"2 masters = {dual:.1f} admission cycles/s vs 1 master = "
        f"{single:.1f}: scaling {scaling:.2f}x is below the 1.8x bar "
        f"(after {retries_used} same-run remeasure(s))")
    # Group-commit run (ISSUE 14): the same contention workload with
    # the store coalescer fusing record mutations into per-shard
    # batches. The selftest bar: strictly under one CAS per admission
    # (the per-record path pays ~2 — one lease put + one delete per
    # cycle), with the 2-vs-1 scaling measurement above untouched.
    gc_cps, cas_per_admission = run_topology(
        masters=1, shards=1,
        group_commit_s=consts.DEFAULT_STORE_GROUP_COMMIT_S)
    assert cas_per_admission < 1.0, (
        f"group commit fused nothing: {cas_per_admission:.2f} store CAS "
        "ops per admission (the per-record path pays ~2)")
    return {
        "multimaster_admission_cps_1": round(single, 1),
        "multimaster_admission_cps_2": round(dual, 1),
        "multimaster_scaling_x": round(scaling, 2),
        "multimaster_scaling_retries": retries_used,
        "multimaster_store_write_rtt_s": MM_STORE_WRITE_RTT_S,
        "multimaster_clients": len(tenants) * clients_per_tenant,
        "multimaster_cas_per_admission_per_record": round(single_cas, 2),
        "store_cas_per_admission": round(cas_per_admission, 3),
        "groupcommit_admission_cps_1": round(gc_cps, 1),
    }


def measure_sustained(clients: int = SUSTAINED_CLIENTS,
                      grpc_mode: str = "threadpool",
                      grpc_workers: int = 32,
                      key: str = "sustained_attach",
                      inflight_bar: int = 500) -> dict:
    """Sustained-load gateway benchmark (ISSUE 6 acceptance, grown a
    client-count parameter for ISSUE 14): N concurrent clients fire one
    single-chip attach each — all in flight at once — through the
    multiplexed front, the shared worker channel pool, and the full
    worker attach path, then detach. Reports ``<key>_rps`` (completed
    attaches / wall-clock of the attach wave), the gateway's peak
    concurrent in-flight requests (must clear ``inflight_bar``), and
    the error count (must be 0). ``grpc_mode="parking"`` runs the
    worker on the parking executor — the 10k-path configuration, where
    ``grpc_workers`` is the ACTIVE budget, not the thread count."""
    from gpumounter_tpu.testing.sim import LiveStack, WorkerRig
    from gpumounter_tpu.utils.config import HostPaths

    root = _bench_root("tpumounter-bench-rps-")
    host = HostPaths(dev_root=f"{root}/dev", proc_root=f"{root}/proc",
                     sys_root=f"{root}/sys",
                     cgroup_root=f"{root}/sys/fs/cgroup",
                     kubelet_socket=f"{root}/pr/kubelet.sock")
    for d in (host.dev_root, host.proc_root, host.cgroup_root):
        os.makedirs(d)
    rig = WorkerRig(host, n_chips=clients, actuator="procroot",
                    use_kubelet_socket=True, informer=True, agent=True)
    # The front must admit every client's connection: above the default
    # 1024-conn bound the 2k config widens it (and the worker pool).
    # At <= 550 both stay None so the historical config is byte-identical.
    stack = LiveStack(rig, grpc_workers=grpc_workers, shared_kube=True,
                      grpc_mode=grpc_mode,
                      gateway_workers=(None if clients <= 1000 else 64),
                      gateway_max_conns=(None if clients <= 1000
                                         else clients + 256))
    pods = [f"load-{i}" for i in range(clients)]
    for name in pods:
        rig.provision_container(rig.sim.add_target_pod(name=name))

    results: dict[str, dict] = {}
    retried: list[str] = []
    barrier = threading.Barrier(clients + 1)
    # transport-class outcomes a client retries under the documented
    # idempotent-retry contract (same X-Request-Id adopts the prior
    # attempt's state instead of double-attaching — docs/guide/FAQ)
    _RETRYABLE = {"UNKNOWN", "UNAVAILABLE", "WorkerCircuitOpen",
                  "WorkerNotFound"}

    def one(pod: str) -> None:
        client = _Client(stack.base)
        path = (f"/addtpu/namespace/default/pod/{pod}"
                "/tpu/1/isEntireMount/false")
        headers = {"X-Request-Id": f"sustained-{pod}"}
        try:
            barrier.wait(timeout=120)
            body = client.request("GET", path, headers=headers)
            if body.get("result") in _RETRYABLE:
                retried.append(pod)
                time.sleep(0.2)
                body = client.request("GET", path, headers=headers)
            results[pod] = body
        except Exception as e:              # noqa: BLE001 — counted
            results[pod] = {"result": f"DRIVER_ERROR: {e}"}
        finally:
            client.close()

    threads = [threading.Thread(target=one, args=(pod,)) for pod in pods]
    try:
        for th in threads:
            th.start()
        barrier.wait(timeout=120)
        t0 = time.monotonic()
        for th in threads:
            th.join(timeout=600)
        elapsed = time.monotonic() - t0
        errors = [(pod, b) for pod, b in results.items()
                  if b.get("result") != "SUCCESS"]
        peak = getattr(stack.http_server, "peak_inflight", 0)
        # bench selftest (same discipline as the contention config): a
        # regression below the concurrency bar or any attach error must
        # FAIL the bench, not publish a plausible-looking number
        error_sample = [f"{p}: {b.get('result')}" for p, b in errors[:5]]
        assert not errors, \
            f"{len(errors)} of {clients} sustained attaches failed: " \
            f"{error_sample}"
        assert peak >= min(inflight_bar, clients - 10), \
            f"gateway peak inflight {peak} never reached the " \
            f"concurrent-in-flight bar ({inflight_bar}) with " \
            f"{clients} clients"
        # detach wave (bounded drivers; not part of the headline number)
        def drain(names: list[str]) -> None:
            client = _Client(stack.base)
            for pod in names:
                client.request(
                    "POST",
                    f"/removetpu/namespace/default/pod/{pod}/force/false",
                    body=b"")
            client.close()
        ok = [pod for pod, b in results.items()
              if b.get("result") == "SUCCESS"]
        drainers = [threading.Thread(
            target=drain, args=(ok[i::16],)) for i in range(16)]
        for th in drainers:
            th.start()
        for th in drainers:
            th.join(timeout=600)
        detail = {
            "clients": clients,
            "gateway_inflight_peak": int(peak),
            "errors": len(errors),
            "error_sample": [f"{p}: {b.get('result')}"
                             for p, b in errors[:3]],
            "idempotent_retries": len(retried),
            "attach_wave_s": round(elapsed, 3),
        }
        executor = getattr(stack.grpc_server, "parking_executor", None)
        if executor is not None:
            status = executor.status()
            detail["worker_active_budget"] = status["max_active"]
            detail["worker_peak_parked"] = status["peak_parked"]
        return {
            f"{key}_rps": round(len(ok) / elapsed, 1),
            key: detail,
        }
    finally:
        stack.close()
        shutil.rmtree(root, ignore_errors=True)


def _round_trip_summary(per_attach: list[dict]) -> dict:
    """Median per-verb apiserver round-trips per attach, plus the median
    total — medians so an occasional TTL-driven discovery refresh doesn't
    smear the steady-state figure."""
    if not per_attach:
        return {}
    verbs = sorted({verb for sample in per_attach for verb in sample})
    summary = {verb: statistics.median(
        [sample.get(verb, 0) for sample in per_attach]) for verb in verbs}
    summary["total"] = statistics.median(
        [sum(sample.values()) for sample in per_attach])
    return {verb: int(count) if float(count).is_integer() else count
            for verb, count in summary.items()}


def tpu_metrics() -> dict | None:
    """Real-chip selftest metrics. None means no TPU backend on this host;
    a hung/crashed selftest is reported as {"ok": false, "error": ...} so
    hardware *failure* is never conflated with hardware *absence*."""
    from gpumounter_tpu.jaxcheck import tpu_selftest
    rc, report, error = tpu_selftest.run_in_subprocess()
    if rc == tpu_selftest.EXIT_NO_TPU:
        return None
    if report is None:
        return {"ok": False, "error": error}
    out = {"ok": report.get("ok", False),
           "backend": report.get("devices", {}).get("backend"),
           "device_count": report.get("devices", {}).get("device_count")}
    if isinstance(report.get("collectives"), dict):
        coll = report["collectives"]
        # a 1-device mesh moves no ICI bytes; carry the mesh size so "ok"
        # can't be mistaken for a multi-chip proof (r2 VERDICT weak #2)
        out["collectives"] = {
            "n_devices": coll.get("n_devices"),
            "degenerate_single_device": coll.get(
                "degenerate_single_device"),
            "ok": coll.get("ok")}
    if isinstance(report.get("training"), dict):
        # toy post-attach smoke config — NOT the perf claim (see "perf")
        out["smoke_train_step_ms"] = report["training"].get("step_ms")
        out["final_loss"] = report["training"].get("final_loss")
    if isinstance(report.get("perf"), dict):
        out["perf"] = {k: report["perf"].get(k) for k in (
            "device_kind", "config", "train_step_ms", "step_ms_incl_sync",
            "model_tflops_per_step", "achieved_tflops", "peak_bf16_tflops",
            "mfu", "tuned", "ok")}
    if isinstance(report.get("pallas_parity"), dict):
        out["pallas_err_vs_oracle"] = \
            report["pallas_parity"].get("err_pallas_vs_oracle")
    if isinstance(report.get("attention_kernels"), dict):
        out["attention_kernels"] = {
            "rows": report["attention_kernels"].get("rows"),
            "ok": report["attention_kernels"].get("ok")}
    if isinstance(report.get("long_context"), dict):
        # flash-attention TRAINING at seq 4096/8192 vs the XLA attempt —
        # the long-context capability claim (round-4 VERDICT next #1)
        out["long_context"] = report["long_context"]
    if isinstance(report.get("roofline"), dict):
        # flagship-step time decomposition justifying the MFU figure
        # (round-4 VERDICT next #5)
        out["roofline"] = {k: report["roofline"].get(k) for k in (
            "measured_step_ms", "measured_mfu", "matmul_pred_ms",
            "matmul_ceiling_mfu", "attention_core_ms", "optimizer_ms",
            "remainder_ms", "explained_fraction", "gemms", "ok")}
    if isinstance(report.get("drain_cycle"), dict):
        out["drain_cycle"] = {k: report["drain_cycle"].get(k) for k in (
            "abs_err", "drain_restore_s", "ok")}
    if isinstance(report.get("backend_reinit"), dict):
        out["backend_reinit_s"] = report["backend_reinit"].get("reinit_s")
    return out


def _pct(sorted_vals: list[float], q: float) -> float:
    return sorted_vals[max(math.ceil(q * len(sorted_vals)) - 1, 0)]


def _compact_tpu(tpu: dict) -> dict:
    """Slim hardware summary for the final stdout line — the full report
    lives in BENCH_DETAIL.json / stderr."""
    out = {"ok": tpu.get("ok"), "backend": tpu.get("backend"),
           "device_count": tpu.get("device_count")}
    perf = tpu.get("perf") or {}
    if perf:
        out["mfu"] = perf.get("mfu")
        out["train_step_ms"] = perf.get("train_step_ms")
    if "pallas_err_vs_oracle" in tpu:
        out["pallas_err_vs_oracle"] = tpu["pallas_err_vs_oracle"]
    if "error" in tpu:
        out["error"] = str(tpu["error"])[:200]
    return out


def main() -> None:
    # overhead mode (no injected delay): 100 cycles so the p99 is a real
    # percentile of the framework's own cost, not the max
    overhead, overhead_detach, _ = measure_attach_cycle(0.0, cycles=100)
    # Phase decomposition of the overhead cycles straight from the worker's
    # own tracing histograms (the LiveStack worker runs in-process, so the
    # registry is shared): where the framework's milliseconds go.
    from gpumounter_tpu.utils.metrics import REGISTRY
    phase_p50_ms = {
        f"attach_{d['phase']}": round(
            REGISTRY.attach_phase.percentile(50, **d) * 1e3, 2)
        for d in REGISTRY.attach_phase.phases()}
    phase_p50_ms.update({
        f"detach_{d['phase']}": round(
            REGISTRY.detach_phase.percentile(50, **d) * 1e3, 2)
        for d in REGISTRY.detach_phase.phases()})
    # Telemetry A/B (ISSUE 7): the overhead config re-measured with
    # lifecycle event emission disabled (what TPU_EVENTS=0 turns off —
    # histogram exemplars are a metrics feature and stay on in both
    # runs). Event emission is lock-free and allocation-light by
    # design; this pins it — the events-ON p50 (the default, measured
    # above) must sit within noise of events-OFF. The bound is generous
    # (1.5x + 2 ms) because both numbers are single-digit milliseconds
    # on a shared machine.
    from gpumounter_tpu.utils.events import EVENTS
    events_were_enabled = EVENTS.enabled
    EVENTS.enabled = False
    try:
        events_off, _, _ = measure_attach_cycle(0.0, cycles=100)
    finally:
        # restore, don't force: under TPU_EVENTS=0 the rest of the bench
        # must keep running in the configuration the environment chose
        EVENTS.enabled = events_were_enabled
    p50_events_on = statistics.median(overhead)
    p50_events_off = statistics.median(events_off)
    assert p50_events_on <= p50_events_off * 1.5 + 0.002, (
        f"event emission is NOT within noise: overhead p50 "
        f"{p50_events_on * 1e3:.2f} ms with events vs "
        f"{p50_events_off * 1e3:.2f} ms without")
    # Usage-sampler A/B (ISSUE 10, same discipline as the events A/B):
    # the overhead config re-measured with TPU_USAGE=0 semantics — no
    # sampler thread at all. Sampling is OFF the attach hot path by
    # construction (own thread, lint-pinned), so the sampler-ON p50
    # (the default, measured above with the sampler ticking every
    # 0.2 s) must sit within noise of sampler-OFF.
    usage_off, _, _ = measure_attach_cycle(0.0, cycles=100, usage=False)
    p50_usage_off = statistics.median(usage_off)
    assert p50_events_on <= p50_usage_off * 1.5 + 0.002, (
        f"usage sampling is NOT within noise: overhead p50 "
        f"{p50_events_on * 1e3:.2f} ms with the sampler vs "
        f"{p50_usage_off * 1e3:.2f} ms without")
    # Topology-plane A/B (ISSUE 17, same discipline): the overhead
    # config re-measured with TPU_TOPOLOGY=0 semantics — no /topoz
    # scrape, no fleet-tick scoring. Serving /topoz is snapshot-only
    # and scoring runs on the fleet tick thread (both lint-pinned), so
    # the topology-ON p50 (the default, measured above with the fleet
    # loop scraping) must sit within noise of topology-OFF.
    topo_off, _, _ = measure_attach_cycle(0.0, cycles=100, topo=False)
    p50_topo_off = statistics.median(topo_off)
    assert p50_events_on <= p50_topo_off * 1.5 + 0.002, (
        f"topology scrape is NOT within noise: overhead p50 "
        f"{p50_events_on * 1e3:.2f} ms with the topology plane vs "
        f"{p50_topo_off * 1e3:.2f} ms without")
    # Parking-executor A/B (ISSUE 14, same discipline as the events/
    # usage A/Bs): the overhead config re-measured over the production
    # worker executor (TPU_GRPC_ASYNC semantics). The 10 ms bar is
    # asserted on THIS number too — the 10k-path configuration itself
    # must hold the p50, not just the legacy thread pool.
    parking_overhead, _, _ = measure_attach_cycle(0.0, cycles=50,
                                                  grpc_mode="parking")
    p50_parking = statistics.median(parking_overhead)
    assert p50_parking <= p50_events_on * 1.5 + 0.002, (
        f"parking executor is NOT within noise: overhead p50 "
        f"{p50_parking * 1e3:.2f} ms parked vs "
        f"{p50_events_on * 1e3:.2f} ms on the thread pool")
    single, single_detach, _ = measure_attach_cycle(0.0, cycles=25,
                                                    n_chips=1, entire=False)
    # entire-NODE attach: 8 chips through one slave pod — the fused
    # actuation configuration (all mknods per container in one crossing)
    multi, _, _ = measure_attach_cycle(0.0, cycles=25, n_chips=8)
    # >=100 e2e cycles so the p99 is a real percentile, not the max
    # (r2 VERDICT weak #8)
    e2e, _, _ = measure_attach_cycle(SCHED_DELAY_S, cycles=100)
    e2e_sorted = sorted(e2e)
    p50 = statistics.median(e2e)
    p99 = _pct(e2e_sorted, 0.99)
    # third config: SAME injected per-slave-pod scheduler delay, but a warm
    # pool sized to cover the attach — a pool hit pays only actuation, so
    # this p50 should sit next to overhead_p50, not next to e2e p50. Also
    # the config that counts apiserver round-trips per attach: with the
    # informer the warm path must show ZERO LISTs.
    hits_before = REGISTRY.pool_hits.value()
    misses_before = REGISTRY.pool_misses.value()
    pool_e2e, _, pool_round_trips = measure_attach_cycle(
        SCHED_DELAY_S, cycles=50, warm_pool=True, count_round_trips=True)
    pool_hits = REGISTRY.pool_hits.value() - hits_before
    pool_misses = REGISTRY.pool_misses.value() - misses_before
    result = {
        "metric": "hot_attach_e2e_p50_latency_4chip_entire_mount",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_P50_S / p50, 2),
        "e2e_p99_s": round(p99, 4),
        "overhead_p50_s": round(statistics.median(overhead), 4),
        "overhead_p99_s": round(_pct(sorted(overhead), 0.99), 4),
        "overhead_p50_events_off_s": round(p50_events_off, 4),
        "events_overhead_delta_ms": round(
            (p50_events_on - p50_events_off) * 1e3, 3),
        "overhead_p50_usage_off_s": round(p50_usage_off, 4),
        "utilz_overhead_delta_ms": round(
            (p50_events_on - p50_usage_off) * 1e3, 3),
        "overhead_p50_topo_off_s": round(p50_topo_off, 4),
        "topoz_scrape_delta_ms": round(
            (p50_events_on - p50_topo_off) * 1e3, 3),
        "overhead_p50_parking_s": round(p50_parking, 4),
        "single_chip_attach_p50_s": round(statistics.median(single), 4),
        "single_chip_detach_p50_s": round(
            statistics.median(single_detach), 4),
        "multi_chip_attach_p50_s": round(statistics.median(multi), 4),
        "detach_p50_s": round(statistics.median(overhead_detach), 4),
        "injected_schedule_delay_s": SCHED_DELAY_S,
        "overhead_phase_p50_ms": phase_p50_ms,
        "pool_hit_e2e_p50_s": round(statistics.median(pool_e2e), 4),
        "pool_hit_e2e_p99_s": round(_pct(sorted(pool_e2e), 0.99), 4),
        "pool_hits": int(pool_hits),
        "pool_misses": int(pool_misses),
        "apiserver_round_trips_per_attach": _round_trip_summary(
            pool_round_trips),
        "cycles": {"overhead": len(overhead), "single": len(single),
                   "multi_chip": len(multi), "e2e": len(e2e),
                   "e2e_with_pool": len(pool_e2e)},
    }
    # Broker contention config: queued-attach wait + preemption e2e
    # (tenant quotas, priority queue — master/admission.py).
    result.update(measure_contention())
    # Multi-master scale-out config: 2 leader-elected masters vs 1 on
    # the contention workload with durable intent (master/shardring.py,
    # master/election.py, master/store.py — docs/guide/HA.md).
    result.update(measure_multimaster())
    # Sustained-load gateway config: >= 500 concurrent in-flight attach
    # RPCs through the multiplexed front (master/httpfront.py).
    result.update(measure_sustained())
    # The 10k admission path (ISSUE 14): the same workload at 2,000
    # concurrent in-flight clients over the parking-executor worker —
    # grpc_workers=32 is the ACTIVE budget; thousands of in-flight RPCs
    # ride parked. Selftest bars: zero errors, >= 1500 peak in-flight
    # at the gateway, and the overhead p50 (measured above on the
    # unloaded config) still under 10 ms.
    result.update(measure_sustained(
        clients=SUSTAINED_2K_CLIENTS, grpc_mode="parking",
        grpc_workers=32, key="sustained_attach_2k", inflight_bar=1500))
    assert result["sustained_attach_2k"]["errors"] == 0
    # the bar holds on BOTH executors: the legacy pool (trajectory
    # comparability) and the parking path the 2k config just ran
    assert result["overhead_p50_s"] < 0.010, (
        f"attach overhead p50 {result['overhead_p50_s'] * 1e3:.2f} ms "
        "regressed past the 10 ms bar the 10k admission path holds")
    assert result["overhead_p50_parking_s"] < 0.010, (
        f"parking-executor attach overhead p50 "
        f"{result['overhead_p50_parking_s'] * 1e3:.2f} ms regressed "
        "past the 10 ms bar")
    tpu = tpu_metrics()
    if tpu is not None:
        result["tpu"] = tpu
    # Full result: stderr + sidecar file (humans / archaeology). Final
    # stdout line: COMPACT summary — the harness parses the LAST stdout
    # line and its tail window truncates multi-KB lines (the "parsed":
    # null failure mode of every selftest-bearing BENCH_r0*.json).
    print(json.dumps(result, indent=2), file=sys.stderr)
    try:
        detail_path = os.path.join(os.path.dirname(os.path.abspath(
            __file__)), "BENCH_DETAIL.json")
        with open(detail_path, "w") as f:
            json.dump(result, f, indent=2)
    except OSError:
        pass
    compact = dict(result)
    if "tpu" in compact:
        compact["tpu"] = _compact_tpu(compact["tpu"])
    sys.stdout.flush()
    print(json.dumps(compact), flush=True)


if __name__ == "__main__":
    main()
