"""Benchmark: hot-attach latency through the full control plane.

Drives the complete AddTPU/RemoveTPU path — HTTP master gateway → gRPC
worker → allocator (slave pods through a scripted scheduler) → real cgroup-v1
device-permission writes + device-node actuation on a fixture host tree, with
the collector reading a real gRPC unix-socket kubelet — and reports the p50
attach latency for a 4-chip entire-mount.

Baseline: the north-star target is < 3 s p50 for a 4-chip host attach
(BASELINE.json; the reference publishes no numbers — BASELINE.md). The
dominant real-world cost the reference pays is its unthrottled slave-pod
poll loop (allocator.go:237-283); this framework's watch-based allocator is
the component under test here. ``vs_baseline`` is target/measured (>1 ⇒
faster than target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_P50_S = 3.0
CYCLES = 25
CHIPS = 4


def main() -> None:
    from gpumounter_tpu.testing.sim import LiveStack, WorkerRig
    from gpumounter_tpu.utils.config import HostPaths

    root = tempfile.mkdtemp(prefix="tpumounter-bench-")
    host = HostPaths(dev_root=f"{root}/dev", proc_root=f"{root}/proc",
                     sys_root=f"{root}/sys",
                     cgroup_root=f"{root}/sys/fs/cgroup",
                     kubelet_socket=f"{root}/pr/kubelet.sock")
    for d in (host.dev_root, host.proc_root, host.cgroup_root):
        os.makedirs(d)

    rig = WorkerRig(host, n_chips=CHIPS, actuator="procroot",
                    use_kubelet_socket=True)
    stack = LiveStack(rig)
    attach = (f"{stack.base}/addtpu/namespace/default/pod/workload"
              f"/tpu/{CHIPS}/isEntireMount/true")
    detach = (f"{stack.base}/removetpu/namespace/default/pod/workload"
              "/force/false")
    try:
        latencies = []
        for _ in range(CYCLES):
            t0 = time.monotonic()
            with urllib.request.urlopen(attach) as resp:
                body = json.loads(resp.read())
            latencies.append(time.monotonic() - t0)
            assert body["result"] == "SUCCESS", body
            req = urllib.request.Request(
                detach,
                data=json.dumps({"uuids": body["device_ids"]}).encode(),
                method="POST")
            with urllib.request.urlopen(req) as resp:
                assert json.loads(resp.read())["result"] == "SUCCESS"
    finally:
        stack.close()

    p50 = statistics.median(latencies)
    print(json.dumps({
        "metric": "hot_attach_p50_latency_4chip_entire_mount",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_P50_S / p50, 1),
    }))


if __name__ == "__main__":
    main()
