"""Benchmark: hot-attach latency through the full control plane.

Drives the complete AddTPU/RemoveTPU path — HTTP master gateway → gRPC
worker → allocator (slave pods through a scripted scheduler) → real cgroup-v1
device-permission writes + device-node actuation on a fixture host tree — and
reports the p50 attach latency for a 4-chip entire-mount.

Baseline: the north-star target is < 3 s p50 for a 4-chip host attach
(BASELINE.json; the reference publishes no numbers — BASELINE.md). The
dominant real-world cost the reference pays is its unthrottled slave-pod
poll loop (allocator.go:237-283); this framework's watch-based allocator is
the component under test here. ``vs_baseline`` is target/measured (>1 ⇒
faster than target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_P50_S = 3.0
CYCLES = 25
CHIPS = 4


def build_stack(root: str):
    from gpumounter_tpu.actuation.cgroup import CgroupDeviceController
    from gpumounter_tpu.actuation.mount import TPUMounter
    from gpumounter_tpu.actuation.nsenter import ProcRootActuator
    from gpumounter_tpu.allocator import TPUAllocator
    from gpumounter_tpu.collector.collector import TPUCollector
    from gpumounter_tpu.collector.fake_kubelet import FakeKubeletServer
    from gpumounter_tpu.collector.podresources import (
        FakePodResourcesClient, KubeletPodResourcesClient)
    from gpumounter_tpu.device.enumerator import PyEnumerator
    from gpumounter_tpu.k8s import objects
    from gpumounter_tpu.k8s.client import FakeKubeClient
    from gpumounter_tpu.master.discovery import WorkerDirectory
    from gpumounter_tpu.master.gateway import MasterGateway
    from gpumounter_tpu.utils.config import HostPaths, Settings
    from gpumounter_tpu.worker.grpc_server import build_server
    from gpumounter_tpu.worker.service import TPUMountService

    host = HostPaths(dev_root=f"{root}/dev", proc_root=f"{root}/proc",
                     sys_root=f"{root}/sys",
                     cgroup_root=f"{root}/sys/fs/cgroup",
                     kubelet_socket=f"{root}/pr/kubelet.sock")
    for d in (host.dev_root, host.proc_root, host.cgroup_root):
        os.makedirs(d)
    for i in range(CHIPS):
        open(f"{host.dev_root}/accel{i}", "w").close()
        with open(f"{host.dev_root}/accel{i}.majmin", "w") as f:
            f.write(f"120:{i}")

    state = FakePodResourcesClient()
    kubelet = FakeKubeletServer(host.kubelet_socket, state).start()
    podres = KubeletPodResourcesClient(host.kubelet_socket)
    enum = PyEnumerator(host, allow_fake=True)
    collector = TPUCollector(enum, podres)

    kube = FakeKubeClient()

    def schedule(pod):
        want = objects.resource_limit(pod, "google.com/tpu")
        assigned = {i for c in state.assignments.values()
                    for r in c.values() for ids in r.values() for i in ids}
        free = [c.uuid for c in enum.enumerate() if c.uuid not in assigned]
        if len(free) < want:
            kube.set_pod_status(
                objects.namespace(pod), objects.name(pod), phase="Pending",
                conditions=[{"type": "PodScheduled", "status": "False",
                             "reason": "Unschedulable"}])
            return
        state.assign(objects.namespace(pod), objects.name(pod), free[:want])
        kube.set_pod_status(objects.namespace(pod), objects.name(pod),
                            phase="Running")

    kube.on_create.append(schedule)
    kube.on_delete.append(
        lambda pod: state.unassign(objects.namespace(pod),
                                   objects.name(pod)))

    settings = Settings()
    settings.host = host
    allocator = TPUAllocator(collector, kube, settings)
    cg = CgroupDeviceController(host, driver="cgroupfs", version=1)
    actuator = ProcRootActuator(host, fake_nodes=True)
    mounter = TPUMounter(cg, actuator, enum, host)
    service = TPUMountService(allocator, mounter, kube, settings)

    cid = "containerd://" + "ab" * 32
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "workload", "namespace": "default",
                        "uid": "uid-w"},
           "spec": {"nodeName": "node-a",
                    "containers": [{"name": "main", "resources": {}}]},
           "status": {"phase": "Running", "qosClass": "BestEffort",
                      "containerStatuses": [{"name": "main",
                                             "containerID": cid}]}}
    kube.put_pod(pod)
    cdir = cg.container_dir(pod, cid)
    os.makedirs(cdir)
    with open(f"{cdir}/cgroup.procs", "w") as f:
        f.write("4242\n")
    os.makedirs(f"{host.proc_root}/4242/root/dev")

    grpc_server, grpc_port = build_server(service, port=0,
                                          address="127.0.0.1")
    grpc_server.start()

    master_kube = FakeKubeClient()
    master_kube.put_pod({"metadata": {"name": "w1", "namespace":
                                      "kube-system",
                                      "labels":
                                      {"app": "tpu-mounter-worker"}},
                         "spec": {"nodeName": "node-a"},
                         "status": {"phase": "Running",
                                    "podIP": "127.0.0.1"}})
    master_kube.put_pod(pod)
    gateway = MasterGateway(master_kube,
                            WorkerDirectory(master_kube,
                                            grpc_port=grpc_port))
    http_server = gateway.serve(port=0, address="127.0.0.1")
    base = f"http://127.0.0.1:{http_server.server_port}"
    return base, (kubelet, grpc_server, http_server)


def measure(base: str) -> list[float]:
    attach = (f"{base}/addtpu/namespace/default/pod/workload/tpu/{CHIPS}"
              "/isEntireMount/true")
    detach = f"{base}/removetpu/namespace/default/pod/workload/force/false"
    latencies = []
    for _ in range(CYCLES):
        t0 = time.monotonic()
        with urllib.request.urlopen(attach) as resp:
            body = json.loads(resp.read())
        latencies.append(time.monotonic() - t0)
        assert body["result"] == "SUCCESS", body
        req = urllib.request.Request(
            detach, data=json.dumps({"uuids": body["device_ids"]}).encode(),
            method="POST")
        with urllib.request.urlopen(req) as resp:
            assert json.loads(resp.read())["result"] == "SUCCESS"
    return latencies


def main() -> None:
    root = tempfile.mkdtemp(prefix="tpumounter-bench-")
    base, servers = build_stack(root)
    try:
        latencies = measure(base)
    finally:
        kubelet, grpc_server, http_server = servers
        http_server.shutdown()
        grpc_server.stop(grace=0)
        kubelet.stop()
    p50 = statistics.median(latencies)
    print(json.dumps({
        "metric": "hot_attach_p50_latency_4chip_entire_mount",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_P50_S / p50, 1),
    }))


if __name__ == "__main__":
    main()
