#!/usr/bin/env bash
# Deploy helper (ref deploy.sh:8-40: deploy | redeploy | uninstall).
set -euo pipefail

MANIFESTS=(
  deploy/namespace.yaml
  deploy/service-account.yaml
  deploy/rbac.yaml
  deploy/tpu-mounter-workers.yaml
  deploy/tpu-mounter-master.yaml
  deploy/tpu-mounter-svc.yaml
)

deploy() {
  for m in "${MANIFESTS[@]}"; do kubectl apply -f "$m"; done
}

uninstall() {
  for ((i=${#MANIFESTS[@]}-1; i>=0; i--)); do
    kubectl delete --ignore-not-found -f "${MANIFESTS[$i]}"
  done
  # namespace deletion is async; redeploy would otherwise apply into a
  # Terminating namespace and fail
  kubectl wait --for=delete namespace/tpu-pool --timeout=120s 2>/dev/null || true
}

case "${1:-}" in
  deploy)    deploy ;;
  redeploy)  uninstall; deploy ;;
  uninstall) uninstall ;;
  *) echo "usage: $0 deploy|redeploy|uninstall" >&2; exit 1 ;;
esac
